"""Mesh-parallel trainer: the Lightning-module/Trainer replacement.

Capability parity with replay/nn/lightning/module.py:14-120 (universal model
wrapper: signature-filtered forward, loss with injected logits callback, optimizer/
scheduler factories from replay/nn/lightning/optimizer.py:26 and scheduler.py:24-45)
and the fit/validate/predict flow of notebook 09 (SURVEY.md §3.2-3.3).

TPU design — one SPMD program instead of DDP:

* A :class:`jax.sharding.Mesh` over all devices with axes
  ``("data", "model", "seq")``. Every placement decision — batch rows on
  ``data``, vocab tables on ``model`` (tensor parallelism for huge catalogs,
  SURVEY.md §2.9 TP row), sequence positions on ``seq`` (Ring Attention
  sequence parallelism for long contexts) — derives from ONE logical-axis rule
  table (:class:`replay_tpu.parallel.sharding.ShardingRules`); XLA inserts the
  all-reduces/permutes over ICI.
* ``train_step`` / ``eval_step`` are jitted once and reused; batches are
  ``device_put`` with a ``NamedSharding`` so computation follows data.
* Static shapes everywhere: final short batches must be padded by the loader
  (see replay_tpu.data.nn.iterator) and flagged with a ``valid`` row mask which
  flows into the loss (zero weight) and the metrics builder.

The trainer is model-agnostic: the forward kwargs are filtered from the batch by
signature introspection (the reference wrapper's trick), so SasRec (feature_tensors,
padding_mask), Bert4Rec (+ token_mask) and TwoTower share one loop.
"""

from __future__ import annotations

import contextlib
import dataclasses
import inspect
import itertools
import logging
import math
import os
import signal as _signal
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from replay_tpu.metrics.builder import MetricsBuilder
from replay_tpu.obs import (
    CompileTracker,
    ConsoleLogger,
    HealthConfig,
    JsonlLogger,
    MemoryMonitor,
    MultiLogger,
    RunLogger,
    StepTelemetry,
    Tracer,
    TrainerEvent,
    goodput_breakdown,
    traced_iterator,
)
from replay_tpu.obs.health import health_metrics

logger = logging.getLogger("replay_tpu")

Batch = Dict[str, Any]


def _signature_names(func) -> List[str]:
    if func is None:
        return []
    return [p.name for p in inspect.signature(func).parameters.values() if p.name != "self"]


# --------------------------------------------------------------------------- #
# Optimizer / scheduler factories (replay/nn/lightning/optimizer.py:26,
# scheduler.py:24-45 — same roles, optax-native)
# --------------------------------------------------------------------------- #
@dataclass
class LRSchedulerFactory:
    """Learning-rate schedule factory.

    ``kind="constant"`` | ``"step"`` (decay by ``gamma`` every ``step_size``
    optimizer steps, the StepLR equivalent) | ``"warmup_linear"`` (linear 0→lr
    over ``warmup_steps``, the LambdaLR-warmup equivalent) |
    ``"warmup_cosine"`` (linear warmup then cosine decay to 0 over
    ``total_steps``).
    """

    kind: str = "constant"
    step_size: int = 1000
    gamma: float = 0.5
    warmup_steps: int = 100
    total_steps: int = 10_000

    def create(self, learning_rate: float) -> optax.Schedule:
        if self.kind == "constant":
            return optax.constant_schedule(learning_rate)
        if self.kind == "step":
            return optax.exponential_decay(
                learning_rate,
                transition_steps=self.step_size,
                decay_rate=self.gamma,
                staircase=True,
            )
        if self.kind == "warmup_linear":
            return optax.linear_schedule(0.0, learning_rate, transition_steps=self.warmup_steps)
        if self.kind == "warmup_cosine":
            return optax.warmup_cosine_decay_schedule(
                0.0, learning_rate, self.warmup_steps, self.total_steps
            )
        msg = f"Unknown scheduler kind: {self.kind}"
        raise ValueError(msg)


@dataclass
class OptimizerFactory:
    """Optimizer factory: ``adam`` | ``adamw`` | ``sgd`` (+ optional momentum),
    with gradient clipping and a pluggable LR schedule."""

    name: str = "adam"
    learning_rate: float = 1e-3
    weight_decay: float = 0.0
    betas: Tuple[float, float] = (0.9, 0.999)
    momentum: float = 0.0
    clip_grad_norm: Optional[float] = None
    scheduler: Optional[LRSchedulerFactory] = None

    def create(self) -> optax.GradientTransformation:
        lr = self.scheduler.create(self.learning_rate) if self.scheduler else self.learning_rate
        if self.name == "adam":
            core = optax.adam(lr, b1=self.betas[0], b2=self.betas[1])
            if self.weight_decay:
                core = optax.chain(optax.add_decayed_weights(self.weight_decay), core)
        elif self.name == "adamw":
            core = optax.adamw(
                lr, b1=self.betas[0], b2=self.betas[1], weight_decay=self.weight_decay
            )
        elif self.name == "sgd":
            core = optax.sgd(lr, momentum=self.momentum or None)
            if self.weight_decay:
                core = optax.chain(optax.add_decayed_weights(self.weight_decay), core)
        else:
            msg = f"Unknown optimizer: {self.name}"
            raise ValueError(msg)
        if self.clip_grad_norm:
            return optax.chain(optax.clip_by_global_norm(self.clip_grad_norm), core)
        return core


# --------------------------------------------------------------------------- #
# TrainState
# --------------------------------------------------------------------------- #
class TrainState(struct.PyTreeNode):
    """Pure pytree of everything a train step mutates.

    ``bad_steps`` counts optimizer updates the non-finite sentinel discarded
    (NaN/Inf loss or gradient norm): on such steps ``step`` and ``rng`` still
    advance — keeping step ids aligned with the batch stream across resumes —
    but ``params``/``opt_state`` keep their previous values.
    """

    step: jnp.ndarray
    params: Any
    opt_state: Any
    rng: jnp.ndarray
    bad_steps: jnp.ndarray


# --------------------------------------------------------------------------- #
# Resilience: recovery policy + preemption handling (docs/robustness.md)
# --------------------------------------------------------------------------- #
@dataclass
class RecoveryPolicy:
    """When and how ``Trainer.fit`` rolls back a diverging run.

    Two triggers share one response (restore the last checkpoint — which is
    always finite, because the sentinel never lets a non-finite update into the
    state — and back the learning rate off by ``lr_backoff``):

    * ``max_consecutive_bad`` sentinel-skipped steps in a row;
    * a monitored-metric blowup at epoch end: the monitored value went
      non-finite, or worsened past ``blowup_factor`` × the best seen (``mode=
      "min"``: value > best × factor; ``mode="max"``: value < best / factor).
      ``blowup_factor=None`` keeps only the non-finite check.

    ``max_restarts`` bounds the total rollbacks for the fit call; exhausting it
    raises ``RuntimeError`` instead of burning the remaining budget. Rollback
    restores weights/optimizer state only — the batch stream keeps moving
    forward, so the poisoned data window is not replayed.
    """

    max_consecutive_bad: int = 5
    max_restarts: int = 3
    lr_backoff: float = 0.5
    blowup_factor: Optional[float] = None

    def __post_init__(self) -> None:
        if self.max_consecutive_bad < 1:
            msg = "max_consecutive_bad must be >= 1"
            raise ValueError(msg)
        if self.max_restarts < 0:
            msg = "max_restarts must be >= 0"
            raise ValueError(msg)
        if not 0.0 < self.lr_backoff <= 1.0:
            msg = "lr_backoff must be in (0, 1]"
            raise ValueError(msg)
        if self.blowup_factor is not None and self.blowup_factor <= 1.0:
            msg = "blowup_factor must be > 1"
            raise ValueError(msg)


class PreemptionHandler:
    """SIGTERM/SIGINT → request a checkpoint at the next step boundary.

    ``fit`` installs one around its training loop (when a checkpoint manager is
    attached): the first signal only sets a flag, the loop saves a
    position-stamped mid-epoch checkpoint at the current step boundary and
    returns cleanly, and ``fit(resume=True)`` continues from that exact batch.
    A second signal falls through to the previously-installed handler, so a
    double Ctrl-C still force-exits. Off the main thread ``signal.signal``
    is unavailable — installation degrades to a no-op and the flag can only be
    set by test harnesses calling :meth:`request` directly.
    """

    def __init__(self, signals: Sequence[int] = (_signal.SIGTERM, _signal.SIGINT)) -> None:
        self.signals = tuple(signals)
        self.requested = False
        self.signal_name: Optional[str] = None
        self._previous: Dict[int, Any] = {}
        self._installed = False

    def request(self, signum: Optional[int] = None) -> None:
        self.requested = True
        if signum is not None:
            self.signal_name = _signal.Signals(signum).name

    def _handle(self, signum, frame) -> None:
        if self.requested:  # second signal: defer to the original behavior
            previous = self._previous.get(signum)
            if callable(previous):
                previous(signum, frame)
                return
            raise KeyboardInterrupt
        logger.warning(
            "received %s: checkpointing at the next step boundary, then exiting",
            _signal.Signals(signum).name,
        )
        self.request(signum)

    def __enter__(self) -> "PreemptionHandler":
        try:
            for sig in self.signals:
                self._previous[sig] = _signal.signal(sig, self._handle)
            self._installed = True
        except ValueError:  # not the main thread: restore what was installed
            for sig, previous in self._previous.items():
                _signal.signal(sig, previous)
            self._previous.clear()
            self._installed = False
        return self

    def __exit__(self, *exc_info) -> None:
        if self._installed:
            for sig, previous in self._previous.items():
                _signal.signal(sig, previous)
            self._previous.clear()
            self._installed = False


# --------------------------------------------------------------------------- #
# Mesh helpers
# --------------------------------------------------------------------------- #
def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    model_parallel: int = 1,
    seq_parallel: int = 1,
    data_parallel: Optional[int] = None,
) -> Mesh:
    """All (or given) devices arranged as a ``("data", "model", "seq")`` mesh.

    ``model_parallel`` chips shard the vocab/model axis (the CEFusedTP table
    layout), ``seq_parallel`` chips form the Ring Attention sequence axis, and
    the rest are data parallel (``data_parallel`` pins the DP extent
    explicitly; by default it absorbs every remaining chip). On a v5e-8 slice
    the defaults give pure DP over ICI; the trivial size-1 axes cost nothing —
    every ``PartitionSpec`` that does not name them behaves exactly as on the
    old 2-axis mesh.
    """
    devices = list(devices) if devices is not None else jax.devices()
    if model_parallel < 1 or seq_parallel < 1:
        msg = (
            f"model_parallel={model_parallel} and seq_parallel={seq_parallel} "
            "must be >= 1"
        )
        raise ValueError(msg)
    if len(devices) % (model_parallel * seq_parallel):
        msg = (
            f"{len(devices)} devices not divisible by model_parallel="
            f"{model_parallel} x seq_parallel={seq_parallel}"
        )
        raise ValueError(msg)
    inferred = len(devices) // (model_parallel * seq_parallel)
    if data_parallel is None:
        data_parallel = inferred
    elif data_parallel != inferred:
        msg = (
            f"data_parallel={data_parallel} inconsistent with {len(devices)} "
            f"devices / (model_parallel={model_parallel} x "
            f"seq_parallel={seq_parallel}) = {inferred}"
        )
        raise ValueError(msg)
    grid = np.array(devices).reshape(data_parallel, model_parallel, seq_parallel)
    return Mesh(grid, ("data", "model", "seq"))


def _batch_sharding(
    mesh: Mesh, rules: Any = None, batch_dim_field: str = "padding_mask"
) -> Callable[[Any], Any]:
    """Place a batch pytree from the rule table: rows over the ``batch`` rule's
    mesh axis, sequence positions over the ``length`` rule's.

    Which leaves are data-parallel is decided by the batch dimension itself: a
    leaf whose leading axis equals ``batch[batch_dim_field]``'s is a per-row
    tensor and shards over the batch axis; anything else (e.g. a shared ``[N]``
    negative-id pool) is replicated. A per-row leaf whose SECOND axis equals the
    reference's sequence length additionally shards it over the ``length`` axis
    (the SP input layout — ``[B, L]`` features arrive ``[B/dp, L/sp]`` per
    chip). Multi-host, sharded leaves are assembled with
    ``jax.make_array_from_process_local_data`` — each process contributes
    ITS disjoint slice (the Partitioning seam's contract) and the global batch
    is local × process_count; replicated leaves must be identical on every host.
    """
    from replay_tpu.parallel.sharding import ShardingRules

    if rules is None:
        rules = ShardingRules.default()
    multiprocess = jax.process_count() > 1
    scale = jax.process_count() if multiprocess else 1
    batch_axis = rules.mesh_axis("batch")
    length_axis = rules.mesh_axis("length")
    batch_size_div = rules.axis_size(mesh, "batch")
    length_div = rules.axis_size(mesh, "length")

    def put(batch):
        reference = batch.get(batch_dim_field)
        local_batch = np.asarray(reference).shape[0] if reference is not None else None
        seq_len = (
            np.asarray(reference).shape[1]
            if reference is not None and np.asarray(reference).ndim >= 2
            else None
        )

        def place(x):
            x = np.asarray(x)
            is_batch_leaf = (
                x.ndim >= 1
                and local_batch is not None
                and x.shape[0] == local_batch
                and (local_batch * scale) % max(batch_size_div, 1) == 0
            )
            if is_batch_leaf:
                axes = [batch_axis] + [None] * (x.ndim - 1)
                if (
                    length_axis is not None
                    and length_div > 1
                    and x.ndim >= 2
                    and seq_len is not None
                    and x.shape[1] == seq_len
                    and seq_len % length_div == 0
                ):
                    axes[1] = length_axis
                sharding = NamedSharding(mesh, P(*axes))
            else:
                sharding = NamedSharding(mesh, P())
            if multiprocess:
                return jax.make_array_from_process_local_data(sharding, x)
            return jax.device_put(x, sharding)

        return jax.tree.map(place, batch)

    return put


def _place_tree(tree: Any, shardings: Any) -> Any:
    """Place host arrays under their shardings — multi-host aware: with several
    processes, every leaf becomes a GLOBAL array assembled from identical
    process-local data (params/state are replicated; all hosts compute the same
    values from the same seed)."""
    if jax.process_count() > 1:

        def place(x, s):
            if isinstance(x, jax.Array) and not x.is_fully_addressable:
                # already a global array (e.g. a multi-host orbax restore that
                # targeted these same shardings) — cannot be host-fetched, and
                # needs no re-placement when the sharding already matches
                return x if x.sharding == s else jax.device_put(x, s)
            return jax.make_array_from_process_local_data(s, np.asarray(x))

        return jax.tree.map(place, tree, shardings)
    return jax.tree.map(jax.device_put, tree, shardings)


def _local_rows(array: jnp.ndarray) -> np.ndarray:
    """This process's rows of a batch-dim global array (identity in
    single-process runs, where every array is fully addressable).

    The output sharding of an eagerly-applied op (e.g. ``lax.top_k`` on the
    jitted eval logits) is XLA's choice, not ours: it may keep the row
    sharding OR replicate. Shards are therefore deduplicated by their global
    row offset (replicated layouts repeat the same rows on every device), and
    a fully-replicated result is cut back to the contiguous row range this
    process contributed (``make_array_from_process_local_data`` lays the
    global batch out in process order)."""
    if jax.process_count() == 1 or getattr(array, "is_fully_addressable", True):
        return np.asarray(array)
    by_offset: Dict[int, Any] = {}
    for shard in array.addressable_shards:
        by_offset.setdefault(shard.index[0].start or 0, shard)
    rows = np.concatenate(
        [np.asarray(by_offset[start].data) for start in sorted(by_offset)], axis=0
    )
    per_process = array.shape[0] // jax.process_count()
    if rows.shape[0] == array.shape[0]:
        # replicated output: every process sees the whole batch — keep only
        # the rows this process fed in (local x process_count == global)
        start = jax.process_index() * per_process
        rows = rows[start : start + per_process]
    if rows.shape[0] != per_process:
        # a partially-replicated layout XLA might invent would silently
        # duplicate/drop users in the metric accumulation — fail loudly
        msg = (
            f"_local_rows: addressable shards of a [{array.shape[0]}, ...] array "
            f"with sharding {array.sharding} cover {rows.shape[0]} distinct rows; "
            f"expected this process's {per_process} — unsupported output layout"
        )
        raise ValueError(msg)
    return rows


def _globalize_scalars(mesh: Mesh, tree: Any) -> Any:
    """Multi-host: promote process-local leaves (e.g. adam's ``count`` scalar,
    created by ``tx.init`` outside any mesh) to replicated GLOBAL arrays; leaves
    that already carry a mesh sharding pass through."""
    replicated = NamedSharding(mesh, P())

    def globalize(x):
        if hasattr(x, "sharding") and getattr(x.sharding, "mesh", None) is not None:
            return x
        return jax.make_array_from_process_local_data(replicated, np.asarray(x))

    return jax.tree.map(globalize, tree)


# param placement is rule-table-driven: replay_tpu.parallel.sharding owns the
# logical-axis annotations and the logical-name -> mesh-axis table (the old
# "embedding_" path heuristic lived here; params_shardings replaced it)


def _resolve_remat_policy(policy: Any):
    """``Trainer(remat_policy=...)`` spellings → a jax.checkpoint policy
    callable (or None = save nothing, i.e. full rematerialization)."""
    if policy is True or policy == "full":
        return None  # jax.checkpoint default: recompute everything
    if isinstance(policy, str):
        names = {
            "dots": "checkpoint_dots",
            "dots_no_batch": "checkpoint_dots_with_no_batch_dims",
        }
        if policy not in names:
            msg = (
                f"unknown remat_policy {policy!r}; use 'full', 'dots', "
                "'dots_no_batch', or a jax.checkpoint_policies callable"
            )
            raise ValueError(msg)
        return getattr(jax.checkpoint_policies, names[policy])
    if callable(policy):
        return policy
    msg = f"remat_policy must be a string, True, or callable; got {policy!r}"
    raise ValueError(msg)


def _chunk_schedule(
    batches: Iterable[Batch],
    chunk: int,
    health_every: Optional[int] = None,
    start: int = 0,
):
    """Group an executable batch stream into scan chunks and single steps.

    Yields ``("scan", [batch] * chunk)`` for full groups and
    ``("step", batch)`` otherwise. A step whose 1-based executed position
    (counted from ``start``, i.e. the fit's ``measured_total``) lands on a
    ``health_every`` cadence boundary is emitted singly — it must run through
    the health-instrumented per-step program, not the health-free scan — as
    are the (< chunk) leftovers before such a boundary and the epoch's short
    tail. Order is always the stream order; only the dispatch granularity
    changes. With ``health_every ≡ 1 (mod chunk)`` every inter-health gap
    packs into full chunks (docs/performance.md "Closing the dispatch gap").
    """
    buffered: List[Batch] = []
    position = start

    def flush():
        # leftovers shorter than a full chunk run per-step: ONE compiled scan
        # length + the per-step program, never a zoo of chunk-length variants
        for leftover in buffered:
            yield ("step", leftover)
        buffered.clear()

    for batch in batches:
        position += 1
        if health_every and position % health_every == 0:
            yield from flush()
            yield ("step", batch)
            continue
        buffered.append(batch)
        if len(buffered) == chunk:
            yield ("scan", list(buffered))
            buffered.clear()
    yield from flush()


# --------------------------------------------------------------------------- #
# Trainer
# --------------------------------------------------------------------------- #
@dataclass
class Trainer:
    """Fit / validate / predict driver around a flax model + loss.

    :param model: flax module with ``__call__`` (training forward → hidden
        states), ``get_logits(hidden, candidates)`` and ``forward_inference``.
    :param loss: a replay_tpu.nn.loss callable; its ``logits_callback`` is bound
        per step to the model's ``get_logits``.
    :param optimizer: optimizer factory (default Adam 1e-3).
    :param mesh: device mesh; default = all devices, pure data parallel.
    :param shard_vocab: shard embedding tables over the ``model`` mesh axis
        (shorthand for the default rule table's ``vocab -> "model"`` row).
    :param sharding_rules: a :class:`~replay_tpu.parallel.sharding.ShardingRules`
        table mapping logical axis names (``"batch"``, ``"length"``,
        ``"vocab"``, ...) to mesh axes. Defaults to
        ``ShardingRules.default(shard_vocab=...)`` — batch rows over ``data``,
        sequence positions over ``seq``, vocab tables over ``model`` when
        ``shard_vocab``. EVERY placement (params, optimizer state, batches,
        activation constraints, the CEFusedTP table layout) derives from this
        one table (docs/distributed_and_serving.md "One rule table").
    :param remat_policy: activation checkpointing for the encoder stack:
        ``None`` (off) / ``"full"`` (save nothing across blocks) / ``"dots"``
        (save MXU outputs only) / ``"dots_no_batch"`` / a
        ``jax.checkpoint_policies`` callable. The model is cloned with
        ``remat=True`` and the policy plumbed into its ``nn.remat``-wrapped
        blocks — the HBM-for-FLOPs trade the L=1024 bench rows A/B
        (docs/performance.md "Remat: trading FLOPs for HBM").
    :param precision: mixed-precision rung (``"bf16"`` / ``"f32"`` /
        :class:`~replay_tpu.nn.Precision`): bf16 activations+compute with f32
        master params, optimizer state and loss accumulation — loss-scale-free
        on TPU, parity-gated against f32 (docs/performance.md "The precision
        ladder"). ``None`` (default) changes nothing.
    :param label_field / mask fields: batch keys produced by the transform
        templates (replay_tpu.nn.transform.template).
    """

    model: Any
    loss: Any
    optimizer: OptimizerFactory = field(default_factory=OptimizerFactory)
    mesh: Optional[Mesh] = None
    shard_vocab: bool = False
    # the ONE logical-axis rule table (parallel.sharding); None = the default
    # DP×TP×SP table derived from shard_vocab
    sharding_rules: Optional[Any] = None
    # activation checkpointing over the transformer blocks: None | "full" |
    # "dots" | "dots_no_batch" | a jax.checkpoint_policies callable
    remat_policy: Optional[Any] = None
    seed: int = 0
    feature_field: str = "feature_tensors"
    padding_mask_field: str = "padding_mask"
    label_field: str = "positive_labels"
    target_mask_field: str = "target_padding_mask"
    negative_field: str = "negative_labels"
    # every jitted path registers here: compile_tracker.report() shows traces
    # (== compiled programs; 1 per fn under the static-shapes invariant) and
    # compile wall-time, surfaced by fit's on_fit_end event
    compile_tracker: CompileTracker = field(default_factory=CompileTracker)
    # host-side span tracer (obs.trace): an ENABLED Tracer here (or passed to
    # fit as tracer=...) records data_wait/h2d/compile/train_step/validation/
    # checkpoint/recovery spans, a trace.json Chrome trace and per-epoch
    # goodput breakdowns; None = tracing off, the span hooks cost ~nothing
    tracer: Optional[Tracer] = None
    # in-graph model-health diagnostics (obs.health): a HealthConfig here
    # extends the jitted train step with per-group grad/param/update norms,
    # update ratios, activation stats, attention entropy, logits stats and
    # embedding coverage — all device-resident, fetched every `cadence` steps
    # by fit and emitted as a `health` payload (docs/performance.md "Model
    # health"). None = the step lowers exactly as before (no extra HLO).
    health: Optional[HealthConfig] = None
    # mixed-precision policy (docs/performance.md "The precision ladder"):
    # "bf16" / "f32" / a replay_tpu.nn.Precision. Applied at construction —
    # the model is cloned with its flax compute `dtype` set to the rung's
    # compute dtype (bf16 activations/compute; MASTER params and optimizer
    # state stay f32 via flax's param_dtype default) and loss-consumed logits
    # are up-cast to the rung's f32 accumulation dtype. None = untouched:
    # every program lowers byte-identical to the pre-precision trainer.
    precision: Optional[Any] = None

    def __post_init__(self) -> None:
        if isinstance(self.loss, str):
            from replay_tpu.nn import loss as loss_zoo

            # only losses constructible with no arguments qualify as shorthands;
            # parametrized ones (SCE, LogInCE, LogOutCE, sampled variants) need
            # an explicit instance
            by_name = {name.lower(): getattr(loss_zoo, name) for name in ("CE", "BCE")}
            if self.loss.lower() not in by_name:
                msg = (
                    f"Unknown loss shorthand {self.loss!r}; use one of "
                    f"{sorted(by_name)}, or pass a replay_tpu.nn.loss instance "
                    "(losses with required parameters, e.g. SCE/LogInCE/LogOutCE, "
                    "must be instantiated by the caller)"
                )
                raise ValueError(msg)
            self.loss = by_name[self.loss.lower()]()
        from replay_tpu.nn.precision import Precision

        self.precision = Precision.resolve(self.precision)
        if self.precision is not None:
            # bf16 rung: the model computes in bf16 through its flax dtype
            # field while params (and therefore optimizer state, gradients and
            # the sentinel arithmetic) stay f32 — loss-scale-free on TPU
            self.model = self.precision.apply_to_model(self.model)
        if self.remat_policy is not None:
            # activation-checkpointed blocks: clone the model with remat on
            # and the policy plumbed to its nn.remat-wrapped encoder stack
            if not hasattr(self.model, "remat"):
                msg = (
                    f"remat_policy={self.remat_policy!r} needs a model with a "
                    f"remat field (SasRec/Bert4Rec); {type(self.model).__name__} "
                    "has none"
                )
                raise ValueError(msg)
            policy = _resolve_remat_policy(self.remat_policy)
            self.model = self.model.clone(remat=True, remat_policy=policy)
        if self.mesh is None:
            self.mesh = make_mesh()
        from replay_tpu.parallel.sharding import ShardingRules

        if self.sharding_rules is None:
            rules = ShardingRules.default(shard_vocab=self.shard_vocab)
            # hand-built legacy meshes may lack an axis the default table
            # names (e.g. a bare ("data", "model") mesh has no "seq"): the
            # DEFAULT table degrades those rules to replicated; an EXPLICIT
            # table still validates strictly
            mesh_axes = set(dict(self.mesh.shape))
            for logical, target in list(rules.rules.items()):
                targets = target if isinstance(target, tuple) else (target,)
                if any(axis is not None and axis not in mesh_axes for axis in targets):
                    rules = rules.with_rule(logical, None)
            self.sharding_rules = rules
        self.sharding_rules.validate(self.mesh)
        if (
            self.sharding_rules.axis_size(self.mesh, "length") > 1
            and getattr(self.model, "use_flash", None) != "ring"
        ):
            # sequence parallelism without the ring route would make XLA
            # all-gather the full sequence for every [B, 1, L, L] attention —
            # exactly the collective the SP path exists to avoid
            msg = (
                "sharding rule 'length' maps to a "
                f"{self.sharding_rules.axis_size(self.mesh, 'length')}-way mesh "
                "axis, but the model does not route attention through ring "
                "attention. Construct it with use_flash='ring' "
                "(SasRec/Bert4Rec), or drop seq_parallel from the mesh."
            )
            raise ValueError(msg)
        self._tx = self.optimizer.create()
        self._put_batch = _batch_sharding(
            self.mesh, self.sharding_rules, self.padding_mask_field
        )
        self._train_step = None
        self._train_scan = None
        # {name: (jitted_fn, abstract arg templates)} — ShapeDtypeStruct
        # snapshots (shape/dtype/sharding, no buffers) of every dispatched
        # program's arguments, recorded once at first dispatch so the static
        # analyses (obs.roofline / obs.profile) can re-lower the EXACT
        # programs later without holding donated state alive
        self._programs: Dict[str, Tuple[Any, Tuple[Any, ...]]] = {}
        self._eval_logits = None
        self._query_embeddings_fn = None
        self._catalog_fn = None
        self.last_step_metrics: Optional[Dict[str, Any]] = None
        # the most recent host-fetched health record (python scalars/lists),
        # refreshed by fit every health.cadence steps
        self.last_health: Optional[Dict[str, Any]] = None
        # live metrics plane (fit(metrics_port=...) / fit(slo_rules=...)):
        # the registry outlives the fit for post-run inspection; the exporter
        # handle exposes the bound port while the fit is live
        self.metrics_registry = None
        self.metrics_exporter = None
        self._lr_scale = 1.0  # RecoveryPolicy backoff multiplier (1.0 = none)
        self._forward_params = _signature_names(type(self.model).__call__)
        self._inference_params = (
            _signature_names(type(self.model).forward_inference)
            if hasattr(type(self.model), "forward_inference")
            else self._forward_params
        )
        # extra batch-supplied kwargs for get_logits (e.g. TwoTower's
        # item_feature_tensors catalog arrays)
        self._logits_extra_params = [
            name
            for name in _signature_names(getattr(type(self.model), "get_logits", None))
            if name not in ("hidden", "candidates_to_score")
        ]
        self.history: List[Dict[str, float]] = []

    # -- state ------------------------------------------------------------- #
    def init_state(self, example_batch: Batch, params: Optional[Any] = None) -> TrainState:
        """Initialize parameters (replicated / vocab-sharded over the mesh).

        ``params`` seeds the state with EXISTING weights instead of a fresh
        init — fresh optimizer moments, step 0. The post-vocabulary-surgery
        path (replay_tpu.nn.vocab): the reference rebuilds its optimizer the
        same way after ``set_item_embeddings_*``.
        """
        rng = jax.random.PRNGKey(self.seed)
        init_rng, state_rng = jax.random.split(rng)
        kwargs = self._forward_kwargs(example_batch)
        logits_extra = {
            name: example_batch[name] for name in self._logits_extra_params if name in example_batch
        }

        def init_fn(module):
            # touch EVERY parameter path: the training forward plus the scoring
            # head (which owns e.g. TwoTower's item tower)
            hidden = module(**kwargs)
            if hasattr(module, "get_logits"):
                module.get_logits(hidden, None, **logits_extra)
            return hidden

        if params is None:
            from replay_tpu.parallel.sharding import sharding_scope

            with sharding_scope(self.sharding_rules, self.mesh):
                params = self.model.init(
                    {"params": init_rng, "dropout": init_rng}, method=init_fn
                )["params"]
        from replay_tpu.parallel.sharding import params_shardings

        shardings = params_shardings(self.mesh, params, self.sharding_rules)
        params = _place_tree(jax.tree.map(np.asarray, params), shardings)
        opt_state = self._tx.init(params)
        if jax.process_count() > 1:
            opt_state = _globalize_scalars(self.mesh, opt_state)
            replicated = NamedSharding(self.mesh, P())
            step, rng, bad_steps = (
                jax.make_array_from_process_local_data(replicated, np.asarray(v))
                for v in (jnp.zeros((), jnp.int32), state_rng, jnp.zeros((), jnp.int32))
            )
            return TrainState(
                step=step, params=params, opt_state=opt_state, rng=rng, bad_steps=bad_steps
            )
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=opt_state,
            rng=state_rng,
            bad_steps=jnp.zeros((), jnp.int32),
        )

    def _forward_kwargs(self, batch: Batch, **overrides) -> Dict[str, Any]:
        """Filter the batch down to the model's forward signature (the reference
        wrapper's introspection trick, replay/nn/lightning/module.py:59)."""
        pool = {**batch, **overrides}
        return {name: pool[name] for name in self._forward_params if name in pool}

    def _scoped(self, fn):
        """``fn`` traced under the rule-table sharding scope: model bodies'
        ``shard_activation`` constraints resolve against THIS trainer's
        (rules, mesh), and the ring-attention route reads its mesh + seq axis
        from the same scope. The context is entered at trace time (inside
        jit), so the python-level scope costs nothing at run time."""
        from replay_tpu.parallel.sharding import sharding_scope

        rules, mesh = self.sharding_rules, self.mesh

        def scoped(*args, **kwargs):
            with sharding_scope(rules, mesh):
                return fn(*args, **kwargs)

        return scoped

    # -- program introspection (obs.profile / obs.roofline) ----------------- #
    def _record_template(self, name: str, jitted_fn, *args) -> None:
        """Snapshot a dispatched program's argument shapes/dtypes/shardings
        (once per name; no device buffers are retained)."""
        if name in self._programs:
            return

        def absify(x):
            # pin only MESH shardings: uncommitted single-device leaves (state
            # scalars created off-mesh) must stay free for jit to place, as
            # they are at real dispatch — pinning their SingleDeviceSharding
            # would conflict with the mesh-sharded params
            sharding = getattr(x, "sharding", None)
            if getattr(sharding, "mesh", None) is None:
                sharding = None
            return jax.ShapeDtypeStruct(jnp.shape(x), x.dtype, sharding=sharding)

        self._programs[name] = (jitted_fn, tuple(jax.tree.map(absify, a) for a in args))

    def lowered_hlo(self, name: str) -> str:
        """The optimized HLO text of a dispatched program (``"train_step"`` /
        ``"train_scan"``), re-lowered from its recorded templates — the input
        to the collective inventory and the no-table-gather guard."""
        if name not in self._programs:
            msg = f"no program {name!r} dispatched yet; known: {sorted(self._programs)}"
            raise KeyError(msg)
        jitted, templates = self._programs[name]
        return jitted.lower(*templates).compile().as_text()

    def analyze_programs(
        self, extra_flops: Optional[Mapping[str, float]] = None
    ) -> Dict[str, Any]:
        """Static roofline/memory/collective record per dispatched program
        (:func:`replay_tpu.obs.roofline.analyze_program`): memory- vs
        compute-bound with the predicted ceiling, the static HBM footprint
        and the collective byte inventory. ``extra_flops`` maps program name
        → analytic FLOPs the cost model cannot see (pallas heads)."""
        from replay_tpu.obs.roofline import analyze_program

        mesh_shape = {axis: int(n) for axis, n in self.mesh.shape.items()}
        out: Dict[str, Any] = {}
        for name, (jitted, templates) in self._programs.items():
            record = analyze_program(
                jitted,
                *templates,
                mesh_shape=mesh_shape,
                extra_flops=(extra_flops or {}).get(name, 0.0),
            )
            if record is not None:
                out[name] = record
        return out

    def _profile_payload(self, profile_dir: str) -> Dict[str, Any]:
        """Post-capture analysis for a profiled fit: the per-named-scope
        device-time attribution (obs.profile) joined against THIS trainer's
        compiled programs, plus their roofline records — one re-compile per
        program, shared by both analyses. Best-effort: a missing capture or
        an analysing-free backend degrades to a partial payload with a logged
        warning, never a failed fit."""
        from replay_tpu.obs.mfu import program_costs
        from replay_tpu.obs.profile import attribute_capture
        from replay_tpu.obs.roofline import analyze_costs

        payload: Dict[str, Any] = {}
        mesh_shape = {axis: int(n) for axis, n in self.mesh.shape.items()}
        texts: Dict[str, str] = {}
        rooflines: Dict[str, Any] = {}
        for name, (jitted, templates) in self._programs.items():
            costs = program_costs(jitted, *templates)
            if costs is None:
                continue
            if costs.get("hlo_text"):
                texts[name] = costs["hlo_text"]
            record = analyze_costs(costs, mesh_shape=mesh_shape)
            if record is not None:
                rooflines[name] = record
        try:
            payload["device_time"] = attribute_capture(profile_dir, texts)
        except (OSError, ValueError) as exc:
            logger.warning(
                "device-time attribution failed for %s: %s", profile_dir, exc
            )
        if rooflines:
            payload["roofline"] = rooflines
        return payload

    # -- train ------------------------------------------------------------- #
    def _build_train_step(self, health: Optional[HealthConfig] = None):
        model, loss, tx = self.model, self.loss, self._tx
        precision = self.precision
        if getattr(loss, "needs_item_embeddings", False) and not hasattr(
            type(model), "get_item_weights"
        ):
            msg = (
                f"{type(loss).__name__} needs the raw item table but "
                f"{type(model).__name__} defines no get_item_weights() method."
            )
            raise ValueError(msg)
        if getattr(loss, "requires_tying_head", False) and not getattr(
            model, "logits_via_item_weights", False
        ):
            msg = (
                f"{type(loss).__name__} reconstructs logits as "
                "hidden . get_item_weights()^T, which only matches get_logits for "
                "bias-free tying-head models (declared via "
                f"logits_via_item_weights=True); {type(model).__name__} makes no "
                "such declaration."
            )
            raise ValueError(msg)
        if getattr(loss, "needs_mesh", False):
            # vocab-sharded losses (CEFusedTP) shard_map over the trainer mesh
            # with their axes taken from the ONE rule table: the catalog over
            # the "vocab" rule, the flattened [B·L, E] rows over the batch
            # (× length, under SP) axes — the loss carries no layout of its own
            loss.mesh = self.mesh
            rules = self.sharding_rules
            if hasattr(loss, "axis_name"):
                vocab_axis = rules.mesh_axis("vocab")
                if vocab_axis is not None:
                    loss.axis_name = vocab_axis
            if hasattr(loss, "data_axis"):
                row_axes = tuple(
                    axis
                    for logical in ("batch", "length")
                    for axis in [rules.mesh_axis(logical)]
                    if axis is not None and rules.axis_size(self.mesh, logical) > 1
                )
                if row_axes:
                    loss.data_axis = row_axes if len(row_axes) > 1 else row_axes[0]
        label_f, tmask_f, neg_f = self.label_field, self.target_mask_field, self.negative_field
        pad_f = self.padding_mask_field

        # `health` branches below are python-static (resolved at trace time,
        # like the models' sow guards): health=None lowers to byte-identical
        # HLO as the pre-health step — golden-tested — while a HealthConfig
        # yields the ONE sanctioned extra compiled variant with an auxiliary
        # `health` pytree of device scalars in the metrics (obs.health).
        def train_step(state: TrainState, batch: Batch):
            if "segment_ids" in batch and "segment_ids" not in self._forward_params:
                # packed batches on a model whose forward cannot take the
                # segment mask: signature filtering would silently DROP the
                # key and attention/loss would cross packed-sequence
                # boundaries — reject (trace-time python check, free at run
                # time), exactly like the flash-route refusal in nn.mask
                msg = (
                    f"batch carries 'segment_ids' (packed sequences) but "
                    f"{type(model).__name__}.__call__ accepts no segment_ids "
                    "parameter — training would silently attend and compute "
                    "loss across packed segment boundaries. Use an unpacked "
                    "batcher for this model, or plumb segment_ids through "
                    "its attention path (nn.mask.segment_attention_mask)."
                )
                raise ValueError(msg)
            rng, dropout_rng, loss_rng = jax.random.split(state.rng, 3)
            # batch-padding rows (fixed-shape final batch) get zero loss weight:
            # gate the target mask by the `valid` row flags from the batcher
            target_mask = batch[tmask_f]
            if "valid" in batch:
                target_mask = target_mask & batch["valid"][
                    (slice(None),) + (None,) * (target_mask.ndim - 1)
                ]

            def loss_fn(params):
                kwargs = {
                    name: batch[name] for name in self._forward_params if name in batch
                }
                if "deterministic" in self._forward_params:
                    kwargs["deterministic"] = False
                # named scopes label the lowered HLO so a jax.profiler device
                # trace correlates with the host-side Tracer spans by name
                with jax.named_scope("forward"):
                    if health is not None and health.capture_intermediates:
                        # mutable `intermediates`: the bodies' sow sites
                        # (stage stats, attention entropy) become live
                        hidden, variables = model.apply(
                            {"params": params},
                            rngs={"dropout": dropout_rng},
                            mutable=["intermediates"],
                            **kwargs,
                        )
                        intermediates = variables.get("intermediates", {})
                    else:
                        hidden = model.apply(
                            {"params": params}, rngs={"dropout": dropout_rng}, **kwargs
                        )
                        intermediates = {}
                logits_extra = {
                    name: batch[name] for name in self._logits_extra_params if name in batch
                }
                logits_callback = partial(
                    model.apply, {"params": params}, method=type(model).get_logits, **logits_extra
                )
                if precision is not None and precision.casts_logits:
                    # f32 loss accumulation under a narrow compute dtype:
                    # candidate-shaped logits are a bf16×bf16 einsum and need
                    # the explicit up-cast (full-catalog logits already
                    # promote through the f32 item table)
                    logits_callback = precision.wrap_logits_callback(logits_callback)
                loss.logits_callback = logits_callback
                if getattr(loss, "needs_item_embeddings", False):
                    # SCE-style losses mine hard negatives from the raw item table
                    loss.item_embeddings_callback = partial(
                        model.apply, {"params": params}, method=type(model).get_item_weights
                    )
                if getattr(loss, "needs_rng", False):
                    loss.rng = loss_rng
                with jax.named_scope("loss"):
                    loss_value = loss(
                        hidden,
                        batch.get("feature_tensors", {}),
                        batch[label_f],
                        batch.get(neg_f),
                        batch[pad_f],
                        target_mask,
                    )
                if health is None:
                    return loss_value
                return loss_value, (hidden, intermediates)

            if health is None:
                loss_value, grads = jax.value_and_grad(loss_fn)(state.params)
            else:
                (loss_value, (hidden, intermediates)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(state.params)
            # non-finite sentinel: one fused flag decides, in-jit, whether this
            # update may touch the state. A NaN/Inf loss or gradient norm keeps
            # the previous params/opt_state (jnp.where select — no host round
            # trip, static shapes preserved); step/rng still advance so step
            # ids stay aligned with the batch stream across resumes.
            grad_norm = optax.global_norm(grads)
            good = jnp.isfinite(loss_value) & jnp.isfinite(grad_norm)
            updates, opt_state = tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)

            metrics = {"loss": loss_value, "good": good, "grad_norm": grad_norm}
            if health is not None:
                logits = None
                streamed_stats = None
                if health.logits_stats and hasattr(type(model), "get_logits"):
                    # last-position scoring-head stats (the catalog logits the
                    # inference path serves) — cheap next to the loss's scoring
                    last_hidden = hidden[:, -1, :] if hidden.ndim == 3 else hidden
                    if getattr(loss, "avoid_full_logits", False):
                        # memory-wall losses (CEFused/CEFusedTP/SCE/GBCE) never
                        # materialize [B, I] logits — neither may health. For
                        # bias-free tying heads the same stats stream over
                        # catalog chunks (obs.health.streamed_logits_stats);
                        # anything else is flagged skipped IN the record (a
                        # numeric sentinel: every sink stays scalar-typed) —
                        # never silently absent.
                        if getattr(model, "logits_via_item_weights", False) and hasattr(
                            type(model), "get_item_weights"
                        ):
                            from replay_tpu.obs.health import streamed_logits_stats

                            table = model.apply(
                                {"params": state.params},
                                method=type(model).get_item_weights,
                            )
                            with jax.named_scope("health_logits"):
                                streamed_stats = streamed_logits_stats(
                                    last_hidden, table
                                )
                        else:
                            streamed_stats = {"skipped": jnp.float32(1.0)}
                            logger.warning(
                                "health.logits_stats: %s avoids full logits and "
                                "%s has no bias-free tying head to stream stats "
                                "from — the health record carries "
                                "logits={'skipped': 1.0} instead",
                                type(loss).__name__,
                                type(model).__name__,
                            )
                    else:
                        logits_extra = {
                            name: batch[name]
                            for name in self._logits_extra_params
                            if name in batch
                        }
                        with jax.named_scope("health_logits"):
                            logits = model.apply(
                                {"params": state.params},
                                last_hidden,
                                None,
                                method=type(model).get_logits,
                                **logits_extra,
                            )
                with jax.named_scope("health"):
                    health_tree = health_metrics(
                        health, state.params, grads, updates, intermediates, logits
                    )
                if streamed_stats is not None:
                    health_tree["logits"] = streamed_stats
                health_tree["grad_norm_global"] = grad_norm
                metrics["health"] = health_tree

            def keep(new, old):
                return jnp.where(good, new, old)

            new_state = TrainState(
                step=state.step + 1,
                params=jax.tree.map(keep, params, state.params),
                opt_state=jax.tree.map(keep, opt_state, state.opt_state),
                rng=rng,
                bad_steps=state.bad_steps + (~good).astype(jnp.int32),
            )
            return new_state, metrics

        return self._scoped(train_step)

    def _h2d_span(self):
        """A ``h2d`` span when an enabled tracer is attached, else a no-op."""
        if self.tracer is not None and self.tracer.enabled:
            return self.tracer.span("h2d")
        return contextlib.nullcontext()

    def traced_train_step(
        self, state: TrainState, batch: Batch
    ) -> Tuple[TrainState, jnp.ndarray]:
        """:meth:`train_step` under the attached tracer's ``train_step`` span.

        Blocks on the loss inside the span (dispatch is async — an unfenced
        span would time the enqueue, not the step) and carves XLA build time
        out of any step that triggered a (re)trace into a nested ``compile``
        span. Falls back to a plain :meth:`train_step` when tracing is off.
        Shared by ``fit``'s traced loop and the multi-chip dry run.
        """
        tracer = self.tracer
        if tracer is None or not tracer.enabled:
            return self.train_step(state, batch)
        compile_before = self.compile_tracker.total_compile_seconds
        with tracer.span("train_step") as step_span:
            state, loss_value = self.train_step(state, batch)
            jax.block_until_ready(loss_value)
        compile_delta = self.compile_tracker.total_compile_seconds - compile_before
        if compile_delta > 0:
            tracer.carve(step_span, "compile", compile_delta)
        return state, loss_value

    def train_step(self, state: TrainState, batch: Batch) -> Tuple[TrainState, jnp.ndarray]:
        """One jitted optimizer step on a (data-sharded) batch.

        Returns ``(state, loss)``; the full step metrics — ``loss``, the
        sentinel's ``good`` flag and ``grad_norm``, all device scalars — stay
        readable on :attr:`last_step_metrics` until the next step.
        """
        if self._train_step is None:
            self._train_step = jax.jit(
                self.compile_tracker.wrap(self._build_train_step(self.health), "train_step"),
                donate_argnums=0,
            )
        with self._h2d_span():
            placed = self._put_batch(batch)
        self._record_template("train_step", self._train_step, state, placed)
        with self.compile_tracker.observe("train_step"):
            new_state, metrics = self._train_step(state, placed)
        self.last_step_metrics = metrics
        return new_state, metrics["loss"]

    def _ensure_train_scan(self):
        """The jitted K-step ``lax.scan`` program, built lazily (and rebuilt
        after anything that invalidates the per-step program: an LR-backoff
        rollback, a vocabulary resize).

        The scan path stays health-free: stacking K per-step health pytrees
        would multiply the metrics payload by K for a path whose whole point
        is minimal host involvement — ``fit(scan_chunk=...)`` interleaves
        health-instrumented single steps at the fetch cadence instead.

        Donation contract (the device feed leans on this): ONLY the TrainState
        argument is donated. The ``[K, ...]`` batch chunk is never donated, so
        a chunk pre-placed by :class:`~replay_tpu.data.nn.DevicePrefetcher`
        while the previous chunk executes cannot alias buffers this dispatch
        will invalidate.
        """
        if self._train_scan is None:
            step_fn = self._build_train_step(None)
            self._train_scan = jax.jit(
                self.compile_tracker.wrap(
                    lambda s, stacked: jax.lax.scan(step_fn, s, stacked), "train_scan"
                ),
                donate_argnums=0,
            )
        return self._train_scan

    @staticmethod
    def _stack_chunk(batches: Sequence[Batch]) -> Batch:
        """K same-shape host batches stacked into one ``[K, ...]`` pytree (the
        scan program's ``xs``), with a clear error for the one sanctioned
        shape relaxation that cannot feed a scan."""
        try:
            return jax.tree.map(
                lambda *xs: np.stack([np.asarray(x) for x in xs]), *list(batches)
            )
        except ValueError as exc:
            msg = (
                "scan chunking stacks every batch of a chunk into one fixed "
                f"[K, ...] program input, but stacking failed: {exc}. All "
                "batches must share one shape and key structure — length-"
                "bucketed batchers (SequenceBatcher(bucket_boundaries=...)) "
                "emit a SET of widths and cannot drive fit(scan_chunk=...)."
            )
            raise ValueError(msg) from exc

    def train_steps(
        self, state: TrainState, batches: Sequence[Batch]
    ) -> Tuple[TrainState, np.ndarray]:
        """``len(batches)`` optimizer steps in ONE XLA dispatch (``lax.scan``).

        Amortizes host→device dispatch latency over K steps — the TPU stays busy
        while the host is out of the loop (one compiled program per chunk
        length). Returns the per-step losses as a ``[K]`` array. Identical math
        to K :meth:`train_step` calls. ``fit(scan_chunk=K)`` drives this path
        end-to-end with a device-feed stage overlapping the H2D copies
        (docs/performance.md "Closing the dispatch gap").
        """
        scan_fn = self._ensure_train_scan()
        stacked = self._stack_chunk(batches)
        with self._h2d_span():
            placed = self._put_stacked(stacked)
        self._record_template("train_scan", scan_fn, state, placed)
        with self.compile_tracker.observe("train_scan"):
            new_state, metrics = scan_fn(state, placed)
        # per-step [K] arrays (loss / sentinel good flags / grad norms)
        self.last_step_metrics = metrics
        return new_state, np.asarray(metrics["loss"])

    def _put_stacked(self, stacked: Batch) -> Batch:
        """Device placement for a [K, ...] stack of batches: the per-row leaves
        shard on their SECOND axis over the ``batch`` rule's mesh axis (axis 0
        is the scan axis) and — under SP — their THIRD (sequence) axis over the
        ``length`` rule's."""
        multiprocess = jax.process_count() > 1
        scale = jax.process_count() if multiprocess else 1
        rules = self.sharding_rules
        batch_axis = rules.mesh_axis("batch")
        length_axis = rules.mesh_axis("length")
        batch_div = max(rules.axis_size(self.mesh, "batch"), 1)
        length_div = rules.axis_size(self.mesh, "length")
        reference = stacked.get(self.padding_mask_field)
        reference = np.asarray(reference) if reference is not None else None
        local_batch = reference.shape[1] if reference is not None else None
        seq_len = reference.shape[2] if reference is not None and reference.ndim >= 3 else None

        def place(x):
            x = np.asarray(x)
            is_batch_leaf = (
                x.ndim >= 2
                and local_batch is not None
                and x.shape[1] == local_batch
                and (local_batch * scale) % batch_div == 0
            )
            if is_batch_leaf:
                axes = [None, batch_axis] + [None] * (x.ndim - 2)
                if (
                    length_axis is not None
                    and length_div > 1
                    and x.ndim >= 3
                    and seq_len is not None
                    and x.shape[2] == seq_len
                    and seq_len % length_div == 0
                ):
                    axes[2] = length_axis
                sharding = NamedSharding(self.mesh, P(*axes))
            else:
                sharding = NamedSharding(self.mesh, P())
            if multiprocess:
                return jax.make_array_from_process_local_data(sharding, x)
            return jax.device_put(x, sharding)

        return jax.tree.map(place, stacked)

    def _chunk_placer(self, tracer: Optional[Tracer]):
        """The device-feed ``place`` callable for the scan-chunked fit: stack
        + place a chunk on the FEEDER thread, so the next chunk's H2D copy
        overlaps the running chunk's compute. Single-step items pass through
        unplaced — the per-step path places its own batch (pre-placing would
        make ``_put_batch``'s ``np.asarray`` round-trip them back to host).
        The ``h2d`` span lands on the feeder thread's timeline: ``trace.json``
        shows the overlap, while the fit thread's goodput fractions count only
        what the feed could NOT hide."""

        def place(item):
            kind, payload = item
            if kind != "scan":
                return None
            span = (
                tracer.span("h2d", steps=len(payload))
                if tracer is not None and tracer.enabled
                else contextlib.nullcontext()
            )
            with span:
                placed = self._put_stacked(self._stack_chunk(payload))
                # fence on the feeder thread: the span times the real copy,
                # and the consumer dispatches on already-resident buffers
                jax.block_until_ready(placed)
            return placed

        return place

    def fit(self, *args, **kwargs) -> TrainState:
        try:
            return self._fit_impl(*args, **kwargs)
        except BaseException:
            # a raising fit must not leak the live metrics endpoint: the
            # non-raising exits (and the recovery-exhausted raise) close it
            # in finish_trace; this catches every other exit — data-pipeline
            # errors, checkpoint failures, Ctrl-C — so a scraper never reads
            # a crashed fit as live and the port is free for the next run
            if self.metrics_exporter is not None:
                self.metrics_exporter.close()
                self.metrics_exporter = None
            raise

    def _fit_impl(
        self,
        train_batches: Iterable[Batch] | Callable[[], Iterable[Batch]],
        epochs: int = 1,
        state: Optional[TrainState] = None,
        val_batches: Optional[
            Callable[[], Iterable[Batch]] | Dict[str, Callable[[], Iterable[Batch]]]
        ] = None,
        metrics: Sequence[str] = ("ndcg", "recall", "map"),
        top_k: Sequence[int] = (1, 5, 10),
        item_count: Optional[int] = None,
        postprocessors: Sequence[Callable] = (),
        log_every: int = 100,
        checkpoint_manager=None,
        checkpoint_every: Optional[int] = None,
        resume: bool = False,
        monitor: Optional[str] = None,
        patience: Optional[int] = None,
        mode: str = "max",
        prefetch: int = 0,
        scan_chunk: Optional[int] = None,
        device_feed: bool = True,
        loggers: Optional[RunLogger | Sequence[RunLogger]] = None,
        profile_steps: Optional[Tuple[int, int]] = None,
        profile_dir: Optional[str] = None,
        recovery: Optional[RecoveryPolicy] = None,
        detect_anomalies: Optional[bool] = None,
        handle_preemption: Optional[bool] = None,
        tracer: Optional[Tracer | bool] = None,
        trace_path: Optional[str] = None,
        metrics_port: Optional[int] = None,
        slo_rules: Optional[Sequence[Any]] = None,
        flight_path: Optional[str] = None,
    ) -> TrainState:
        """Train for ``epochs`` passes; validates after each epoch when
        ``val_batches`` is given, appending to :attr:`history`. A dict of
        factories runs several validation streams sequentially (the reference's
        CombinedLoader), prefixing each stream's metric keys with its name.

        ``monitor`` (a history key, e.g. ``"ndcg@10"`` or ``"train_loss"``)
        enables best-state tracking: fit returns the BEST state seen, marks the
        winning checkpoint's metadata, and — with ``patience`` — stops early
        after that many epochs without improvement (Lightning's
        ModelCheckpoint(monitor=...) + EarlyStopping semantics).

        ``train_batches`` may be a re-iterable (e.g. a SequenceBatcher — its
        ``set_epoch`` is called so shuffling advances per epoch), a zero- or
        one-arg callable returning an iterable (the arg is the epoch), or a plain
        one-shot iterator (materialized once if several epochs are requested).

        ``loggers`` attaches run-telemetry sinks (``replay_tpu.obs``): fit then
        emits ``on_fit_start`` / ``on_train_step`` (loss, LR, samples/sec) /
        ``on_validation_end`` / ``on_epoch_end`` / ``on_checkpoint`` /
        ``on_fit_end`` (telemetry summary, compile report, peak device memory)
        events to every sink. ``log_every`` is itself a sink — a
        :class:`~replay_tpu.obs.ConsoleLogger` on the same event stream — so
        the old print path and a ``JsonlLogger`` run directory see identical
        records. With explicit ``loggers`` every step emits an event, costing
        one scalar device sync (the loss; the step counter is tracked on host
        after a one-time fetch, and LR-schedule evaluation is a tiny host-side
        dispatch only when a scheduler is configured); with only ``log_every``
        the cadence is every ``log_every``-th EXECUTED step, counted globally
        across epochs (the old path counted per-epoch stream positions, so
        the exact steps printed can differ from pre-event-layer logs).

        ``profile_steps=(start, stop)`` captures a ``jax.profiler`` trace of
        the half-open step window [start, stop) — counted over steps actually
        executed by this fit call — into ``profile_dir`` (default: the first
        JsonlLogger's ``run_dir/profile``, else ``./jax_profile``). The
        capture is then parsed (``obs.profile``): per-``jax.named_scope``
        DEVICE-time attribution (embed/encoder/final_norm/forward/loss) rides
        ``on_fit_end`` as a ``device_time`` payload, next to a per-program
        ``roofline`` record (``obs.roofline``: memory- vs compute-bound with
        the predicted ceiling, static HBM footprint, collective bytes) —
        rendered by ``obs.report`` as the "device attribution" and "roofline"
        sections (docs/performance.md "Attribution and roofline").

        ``checkpoint_every`` additionally saves MID-epoch every that many steps,
        recording the data-iterator position (epoch + step within the epoch) in
        the checkpoint metadata. ``resume=True`` restores the manager's latest
        checkpoint and fast-forwards the (deterministic, epoch-seeded) batch
        stream to that exact position, so a killed run continues with the same
        loss curve as an uninterrupted one.

        Dispatch amortization (docs/performance.md "Closing the dispatch
        gap"): ``scan_chunk=K`` drives the :meth:`train_steps` ``lax.scan``
        path end-to-end — each epoch's batches are grouped into fixed-K
        chunks dispatched as ONE XLA program (bitwise-identical math to K
        per-step calls), with the short tail on the existing per-step path
        (exactly one extra compiled variant, no dynamic shapes). In front of
        it, ``device_feed=True`` (the default) runs a
        :class:`~replay_tpu.data.nn.DevicePrefetcher`: a feeder thread
        stacks the NEXT chunk and issues its ``device_put`` /
        ``make_array_from_process_local_data`` while the current chunk is
        still executing, so the host→device copy overlaps compute
        (donation-safe: the scan donates only the TrainState, never the
        chunk). Per-step accounting is preserved exactly: the chunk's ``[K]``
        loss/sentinel/grad-norm arrays come to host once per chunk and fan
        back out through the same bookkeeping as the per-step loop —
        ``on_train_step`` cadence, exact ``on_anomaly`` step indices and
        ``bad_steps`` totals, epoch-loss averaging. What moves to chunk
        granularity: ``checkpoint_every`` boundaries crossed inside a chunk
        save once at the chunk end (the state only exists at chunk
        boundaries), preemption exits at the next chunk boundary, and a
        recovery rollback triggered by a mid-chunk step discards the rest of
        that chunk's (already-executed, pre-rollback) accounting while the
        stream position still advances. With a :class:`HealthConfig`
        attached, every ``cadence``-th step is interleaved as a
        health-instrumented single step (the per-step program — no silent
        health loss; pick ``cadence ≡ 1 (mod scan_chunk)`` to keep full
        chunks between them). Requires ONE fixed batch shape:
        ``SequenceBatcher(bucket_boundaries=...)`` is rejected at fit start.

        Resilience (docs/robustness.md): the train step's non-finite sentinel
        always protects the state — a NaN/Inf loss or gradient norm discards
        that update in-jit and bumps ``state.bad_steps``. ``detect_anomalies``
        additionally checks the sentinel flag on host every step and emits an
        ``on_anomaly`` event per skipped step (default: on when ``recovery`` is
        set or explicit ``loggers`` are attached — those paths already pay the
        per-step device sync; off for log_every-only runs, which stay
        sync-free). A ``recovery`` policy counts bad steps regardless:
        ``detect_anomalies=False`` silences the events, never the rollback
        trigger. ``recovery`` attaches a :class:`RecoveryPolicy`: after
        ``max_consecutive_bad`` skipped steps or an epoch-end monitored-metric
        blowup, fit restores the manager's latest checkpoint (or, before any
        save, a snapshot of the initial state), backs the learning rate off,
        emits ``on_recovery`` and continues forward in the batch stream —
        bounded by ``max_restarts``, then ``RuntimeError``. ``handle_preemption``
        (default: on when a ``checkpoint_manager`` is attached) installs
        SIGTERM/SIGINT handlers for the duration of the loop: the first signal
        saves a position-stamped mid-epoch checkpoint at the next step boundary
        and returns the state cleanly, so ``fit(resume=True)`` reproduces the
        uninterrupted run exactly; a second signal force-exits.

        Tracing/goodput (docs/performance.md "Goodput and tracing"):
        ``tracer=True`` (or an ``obs.Tracer`` instance) records host-side
        spans — ``data_wait`` / ``h2d`` / ``compile`` / ``train_step`` /
        ``validation`` / ``checkpoint`` / ``recovery`` — and (a) writes a
        Chrome trace-event ``trace.json`` at fit end to ``trace_path``
        (default: the first JsonlLogger's run dir), (b) adds a ``goodput``
        breakdown (phase fractions summing to 1.0 + ``input_starvation``) to
        every ``on_epoch_end``/``on_fit_end`` event. A tracer passed as an
        ARGUMENT scopes to this fit call (detached at fit end); preattach one
        to :attr:`tracer` to trace every fit. Goodput fractions decompose the
        fit thread's wall clock — spans from other threads (a prefetch
        worker's ``batch_build``) appear in ``trace.json`` only. Tracing
        synchronizes on the loss every step for honest step times, so leave
        it off for maximum-throughput runs. Epoch windows tile the run: each
        closes at its ``on_epoch_end`` emission, so the end-of-epoch
        checkpoint save lands in the NEXT epoch's window.

        Model health (docs/performance.md "Model health"): a
        :class:`~replay_tpu.obs.HealthConfig` on :attr:`health` makes the
        jitted step also compute per-group gradient/parameter/update norms and
        update ratios, activation RMS/absmax per stage, per-head attention
        entropy, logits stats and embedding-row coverage — all on device. Fit
        fetches the record every ``cadence`` steps (one device_get), attaches
        it as a ``health`` payload to the next ``on_train_step`` and to every
        ``on_epoch_end``, and — when the config carries a ``HealthWatcher`` —
        emits ``on_health_warning`` on an EWMA blowup of the grad norm or max
        update ratio, *before* the non-finite sentinel trips; with
        ``trigger_recovery=True`` and a ``recovery`` policy the warning rolls
        back immediately. Enabling health is exactly one compiled train-step
        variant; the cadence is host-side, so no retraces after step 1.

        Live metrics plane (docs/observability.md): ``metrics_port`` attaches
        a :class:`~replay_tpu.obs.MetricsLogger` sink (the existing event
        stream bridged into a thread-safe counters/gauges/histograms registry
        — no new trainer hooks) and serves it for the duration of the fit via
        a stdlib HTTP exporter: ``GET /metrics`` is Prometheus text,
        ``/snapshot`` the JSON view. ``metrics_port=0`` binds an ephemeral
        port (read it from :attr:`metrics_exporter`); a busy port degrades to
        a logged no-op, never a failed fit. ``slo_rules`` (a sequence of
        :class:`~replay_tpu.obs.SLORule`) attaches an
        :class:`~replay_tpu.obs.SLOWatchdog` evaluated after every bridged
        step event: a rule breached for its ``for_steps`` consecutive
        evaluations emits ONE ``on_slo_violation`` through the same sinks
        (console render, events.jsonl, ``replay_slo_violations_total``), and
        the recovery transition emits ``on_slo_recovery`` with the breach
        duration. Either option implies per-step events (the explicit-loggers
        cadence); the registry stays readable after fit on
        :attr:`metrics_registry`. Multi-host fits stamp every event with this
        process's ``process_index`` so ``obs.report`` can merge per-process
        shards and compute cross-host skew.

        Black box (docs/observability.md "The black box and post-mortems"):
        ``flight_path`` attaches a
        :class:`~replay_tpu.obs.BlackboxLogger` — the same event stream,
        recorded into an mmap-backed flight ring whose last N records survive
        SIGKILL (``obs.report --postmortem`` reads what a dead fit was doing).
        Defaults from the ``REPLAY_TPU_FLIGHT_PATH`` env var, which
        ``launch_workers(run_dir=...)`` sets per rank — a worker script needs
        no change to be flight-recorded. Implies per-step events, like any
        explicit sink. On preemption (SIGTERM/SIGINT) the tracer is flushed
        to ``trace_path`` at the ``on_preemption`` boundary — before the
        shutdown-window checkpoint save — so the span tree survives even if
        the save itself dies.
        """
        if checkpoint_manager is not None and not self.history:
            # resume: prior epoch records survive the restart (metric-history
            # state_dict semantics of the reference validation callback)
            self.history = list(checkpoint_manager.history())
        one_shot = None
        if not callable(train_batches) and iter(train_batches) is train_batches:
            # a generator: re-iteration is impossible, materialize once
            one_shot = list(train_batches) if epochs > 1 else train_batches

        def batches_for(epoch: int):
            if one_shot is not None:
                return one_shot
            if callable(train_batches):
                takes_epoch = len(_signature_names(train_batches)) >= 1
                return train_batches(epoch) if takes_epoch else train_batches()
            if hasattr(train_batches, "set_epoch"):
                train_batches.set_epoch(epoch)
            return train_batches

        if mode not in ("max", "min"):
            msg = "mode must be 'max' or 'min'"
            raise ValueError(msg)
        if patience is not None and patience < 1:
            msg = "patience must be >= 1 (it counts consecutive non-improving epochs)"
            raise ValueError(msg)
        def reject_bucketed(source) -> None:
            """Bucketed batchers cannot feed the scan: fail up front with the
            real reason, not an opaque np.stack error mid-epoch. Checked on
            the fit argument AND on what a factory callable returns (the
            factory object itself carries no batcher attributes)."""
            if getattr(source, "bucket_boundaries", None) or (
                hasattr(source, "scan_compatible") and not source.scan_compatible
            ):
                msg = (
                    "fit(scan_chunk=...) stacks K batches into one compiled "
                    "[K, B, L] scan program, which requires ONE fixed batch "
                    f"shape; {type(source).__name__}(bucket_boundaries=...) "
                    "emits a set of widths. Drop the bucketing or the "
                    "scan_chunk (docs/performance.md 'Closing the dispatch "
                    "gap')."
                )
                raise ValueError(msg)

        if scan_chunk is not None:
            scan_chunk = int(scan_chunk)
            if scan_chunk < 1:
                msg = "scan_chunk must be >= 1 (optimizer steps per lax.scan dispatch)"
                raise ValueError(msg)
            reject_bucketed(train_batches)

        if state is not None:
            # continual-training guard (docs/robustness.md): params grown by
            # vocab surgery without their optimizer moments (or vice versa)
            # must fail HERE, naming the table path — not crash deep in
            # optax's first update or silently train on reset moments
            from replay_tpu.nn.vocabulary import validate_optimizer_state

            schema = getattr(self.model, "schema", None)
            if schema is not None:
                validate_optimizer_state(state.params, state.opt_state, schema)

        start_epoch, skip_steps, pending_restore_step = 0, 0, None
        resumed_best_step = None
        pending_stream_cursor = None  # out-of-core resume: seek, don't rescan
        if resume:
            if checkpoint_manager is None:
                msg = "resume=True needs a checkpoint_manager"
                raise ValueError(msg)
            if state is not None:
                msg = (
                    "resume=True restores the manager's latest checkpoint; "
                    "passing state= as well is ambiguous (the explicit state "
                    "would silently win). Drop one of the two."
                )
                raise ValueError(msg)
            latest = checkpoint_manager.latest_step()
            if latest is not None:
                meta = checkpoint_manager.metadata(latest)
                if meta.get("mid_epoch"):
                    start_epoch = int(meta["epoch"])
                    skip_steps = int(meta["step_in_epoch"])
                    # a streaming batcher's resumable position (the PR-2
                    # preemption contract extended to out-of-core runs):
                    # restore_cursor SEEKS to the exact mid-epoch state
                    # instead of re-reading and discarding skip_steps batches
                    # (multi-host: each rank reads ITS per-process sidecar —
                    # the shared one only carries process 0's cursor)
                    pending_stream_cursor = checkpoint_manager.process_metadata(
                        latest
                    ).get("stream_cursor") or meta.get("stream_cursor")
                elif "epoch" in meta:
                    start_epoch = int(meta["epoch"]) + 1
                else:
                    msg = (
                        f"Checkpoint step {latest} carries no data-iterator "
                        "position ('epoch' missing from its metadata — saved by "
                        "an older fit or a manual save_checkpoint); resuming "
                        "would silently retrain from epoch 0 on top of the "
                        "restored weights. Restore explicitly via "
                        "restore_checkpoint and pass state= instead."
                    )
                    raise ValueError(msg)
                if meta.get("lr_scale"):
                    # the killed run had backed its LR off (RecoveryPolicy);
                    # resuming at full rate would rerun the divergence
                    self._set_lr_scale(float(meta["lr_scale"]))
                pending_restore_step = latest
                resumed_best_step = checkpoint_manager.best_step()
                logger.info(
                    "resuming from step %d (epoch %d, fast-forward %d batches)",
                    latest, start_epoch, skip_steps,
                )

        best_value, best_state, stale_epochs = None, None, 0
        if resume and monitor is not None:
            # seed the monitored best from the restored history so a worse
            # post-resume epoch cannot repoint best.json / win the return value
            # NaN-guarded: a fully-fast-forwarded resumed epoch records
            # train_loss=NaN, which would poison max()/min() and freeze `improved`
            seen_values = [
                r[monitor] for r in self.history if monitor in r and math.isfinite(r[monitor])
            ]
            if seen_values:
                best_value = max(seen_values) if mode == "max" else min(seen_values)
            if resumed_best_step is not None:
                # the winning checkpoint's sidecar records the monitored value
                # at mark time (the same channel lr_scale resumes through):
                # it survives a lost/truncated history.json, so the seed never
                # regresses to None just because the history did
                try:
                    sidecar_value = checkpoint_manager.metadata(resumed_best_step).get(monitor)
                except (OSError, ValueError):
                    sidecar_value = None
                if (
                    isinstance(sidecar_value, (int, float))
                    and not isinstance(sidecar_value, bool)
                    and math.isfinite(sidecar_value)
                    and (
                        best_value is None
                        or (mode == "max" and sidecar_value > best_value)
                        or (mode == "min" and sidecar_value < best_value)
                    )
                ):
                    best_value = float(sidecar_value)

        # -- run-telemetry sinks (replay_tpu.obs) -------------------------- #
        explicit_loggers: List[RunLogger] = []
        if loggers is not None:
            # duck-typed: RunLogger is a protocol, a single sink is anything
            # with log_event (a structural conformer need not subclass it)
            explicit_loggers = (
                [loggers] if hasattr(loggers, "log_event") else list(loggers)
            )
        # -- live metrics plane (obs.metrics / obs.exporter / obs.slo) ------ #
        # the MetricsLogger is an explicit sink: live gauges need per-step
        # events, so requesting metrics/SLOs opts into the per-step device
        # sync exactly like attaching a JsonlLogger does
        metrics_logger = None
        if self.metrics_exporter is not None:
            # a previous fit raised before its terminal event: release the
            # port before (maybe) binding a fresh exporter
            self.metrics_exporter.close()
            self.metrics_exporter = None
        if metrics_port is not None or slo_rules:
            from replay_tpu.obs.exporter import MetricsExporter
            from replay_tpu.obs.metrics import MetricsLogger
            from replay_tpu.obs.slo import SLOWatchdog

            metrics_logger = MetricsLogger()
            self.metrics_registry = metrics_logger.registry
            if slo_rules:
                # emit is pointed at the sink fan-out once run_logger exists
                metrics_logger.watchdog = SLOWatchdog(
                    slo_rules, metrics_logger.registry
                )
            explicit_loggers.append(metrics_logger)
            if metrics_port is not None:
                self.metrics_exporter = MetricsExporter(
                    metrics_logger.registry,
                    port=metrics_port,
                    # the identity block /snapshot and /healthz carry, so a
                    # federation scrape can label this fit's series
                    identity={"process_index": jax.process_index()},
                ).start()
        # -- the black box (obs.blackbox): SIGKILL-surviving flight ring ----- #
        # attaching the sink IS the instrumentation: the same event stream
        # every other sink sees, stored as O(1) in-place mmap ring writes.
        # launch_workers(run_dir=...) hands workers their ring path via env,
        # so a fit inside a launched worker is flight-recorded with no
        # worker-script change.
        flight_path = flight_path or os.environ.get("REPLAY_TPU_FLIGHT_PATH")
        flight_logger = None
        if flight_path:
            from replay_tpu.obs.blackbox import BlackboxLogger

            try:
                flight_logger = BlackboxLogger(
                    flight_path,
                    meta={
                        "role": "fit",
                        "pid": os.getpid(),
                        "process_index": jax.process_index(),
                    },
                )
            except OSError as exc:
                # same posture as the exporter: the black box must never take
                # down the run it records
                logger.warning(
                    "flight recorder: cannot open %s (%s); fit runs unrecorded",
                    flight_path, exc,
                )
            else:
                explicit_loggers.append(flight_logger)
        sinks: List[RunLogger] = list(explicit_loggers)
        if log_every:
            # events already arrive at log_every cadence when no explicit
            # sinks ask for per-step records — the console then prints each one
            sinks.append(ConsoleLogger(every=log_every if explicit_loggers else 1))
        run_logger: Optional[RunLogger] = (
            MultiLogger(sinks) if len(sinks) > 1 else (sinks[0] if sinks else None)
        )
        if metrics_logger is not None and metrics_logger.watchdog is not None:
            # violations ride the SAME fan-out as every other event: jsonl,
            # console, tensorboard AND the registry's violation counter
            metrics_logger.watchdog.emit = run_logger.log_event
        event_every = 1 if explicit_loggers else (log_every or 0)

        # -- span tracing + goodput accounting (replay_tpu.obs.trace) ------- #
        prior_tracer = self.tracer
        tracer_from_arg = tracer is not None
        if tracer is True:
            tracer = Tracer()
        if isinstance(tracer, Tracer):
            self.tracer = tracer  # train_step's h2d spans route through it too
        trace = self.tracer if self.tracer is not None and self.tracer.enabled else None
        tracing = trace is not None
        if tracing and trace_path is None:
            queue: List[RunLogger] = list(explicit_loggers)
            while queue:  # MultiLogger nests sinks: search them too
                sink = queue.pop(0)
                if isinstance(sink, JsonlLogger):
                    trace_path = os.path.join(sink.run_dir, "trace.json")
                    break
                if isinstance(sink, MultiLogger):
                    queue.extend(sink.loggers)
        # goodput windows decompose THIS thread's wall clock: other threads'
        # spans (a prefetch worker's batch_build) overlap it rather than
        # consume it, so they stay out of the fractions (trace.json keeps them)
        fit_trace_base = trace.snapshot(only_current_thread=True) if tracing else {}
        fit_summary_base = trace.summary() if tracing else {}
        fit_trace_t0 = time.perf_counter()

        def span(name: str, **args):
            """A trace span when tracing, else a no-op context."""
            return trace.span(name, **args) if tracing else contextlib.nullcontext()

        def trace_window(base: Dict[str, float], t0: float) -> Dict[str, Any]:
            """Goodput record over this thread's spans since (base, t0)."""
            current = trace.snapshot(only_current_thread=True)
            diff = {name: current.get(name, 0.0) - base.get(name, 0.0) for name in current}
            return goodput_breakdown(diff, time.perf_counter() - t0)

        def fit_spans() -> Dict[str, Dict[str, float]]:
            """Per-name span totals over THIS fit (all threads): a reused
            tracer's earlier fits are subtracted out."""
            out: Dict[str, Dict[str, float]] = {}
            for name, entry in trace.summary().items():
                prev = fit_summary_base.get(
                    name, {"count": 0, "seconds": 0.0, "self_seconds": 0.0}
                )
                count = entry["count"] - prev["count"]
                if count > 0:
                    out[name] = {
                        "count": count,
                        "seconds": entry["seconds"] - prev["seconds"],
                        "self_seconds": entry["self_seconds"] - prev["self_seconds"],
                    }
            return out

        def finish_trace() -> None:
            """Terminal tracing work: write trace.json, stop the metrics
            exporter, and detach a tracer that was passed as a fit argument
            (a preattached :attr:`tracer` stays; the argument form scopes to
            this fit)."""
            if tracing and trace_path is not None:
                try:
                    trace.save(trace_path)
                except OSError as exc:
                    logger.warning("trace.json not written to %s: %s", trace_path, exc)
            if tracer_from_arg:
                self.tracer = prior_tracer
            if self.metrics_exporter is not None:
                self.metrics_exporter.close()
                self.metrics_exporter = None
            if flight_logger is not None:
                # one msync so the ring survives machine death up to here;
                # SIGKILL-durability never depended on this close running
                flight_logger.close()

        # multi-host: stamp every event with this process's index so per-
        # process events.jsonl shards merge into ONE cross-host report
        # (obs.report computes step-time skew / the straggler index from it)
        event_process = jax.process_index() if jax.process_count() > 1 else None

        def emit(name: str, step=None, epoch=None, **payload) -> None:
            if run_logger is not None:
                if event_process is not None:
                    payload.setdefault("process_index", event_process)
                run_logger.log_event(
                    TrainerEvent(event=name, step=step, epoch=epoch, payload=payload)
                )
            if name == "on_preemption" and tracing and trace_path is not None:
                # flush the span tree NOW — the preemption paths emit this
                # BEFORE the shutdown-window checkpoint save, so even a save
                # that raises or a scheduler that stops waiting cannot lose
                # the trace of the run being evicted (on_fit_end re-saves
                # over this with the complete tree when it does run)
                try:
                    trace.save(trace_path)
                except OSError as exc:
                    logger.warning(
                        "trace.json not written to %s at preemption: %s",
                        trace_path, exc,
                    )
            if name == "on_fit_end":
                # every non-raising fit exit path ends in exactly one
                # on_fit_end; the raising paths call finish_trace themselves
                finish_trace()

        # -- resilience: anomaly detection / recovery / preemption ---------- #
        # host-side anomaly checks cost one device sync per step, so they
        # default on only where that sync already happens (explicit loggers)
        # or where they are required (a recovery policy); the in-jit sentinel
        # itself is always active and needs no host involvement
        check_anomalies = (
            detect_anomalies
            if detect_anomalies is not None
            else (recovery is not None or bool(explicit_loggers))
        )
        consecutive_bad, restarts = 0, 0
        initial_snapshot = None  # rollback target before any checkpoint exists

        # -- model-health diagnostics (replay_tpu.obs.health) --------------- #
        # the jitted step computes the health pytree every step (device-only);
        # the host fetches it every cadence steps — one small device_get —
        # attaches it to the next emitted on_train_step / the epoch-end event,
        # and feeds the early-warning watcher
        health_cfg = self.health
        health_watcher = health_cfg.watcher if health_cfg is not None else None
        # the scan program is health-free — chunking must not silently drop
        # the diagnostics, so every cadence-th step runs as an interleaved
        # health-instrumented single step (_chunk_schedule breaks chunks there)
        health_every = (
            health_cfg.cadence if (scan_chunk and health_cfg is not None) else None
        )
        if health_every:
            logger.info(
                "scan_chunk=%d with health cadence %d: every %dth step runs "
                "the health-instrumented per-step program (no silent health "
                "loss); cadence ≡ 1 (mod scan_chunk) keeps full chunks "
                "between health steps",
                scan_chunk, health_every, health_every,
            )
        pending_health: Optional[Dict[str, Any]] = None
        last_grad_norm = None  # device scalar; float()ed once per epoch
        # per-fit scope: a second fit must not attach the PREVIOUS fit's last
        # record to its epoch-end events (cadence may exceed a short epoch)
        self.last_health = None

        def do_recovery(reason: str, epoch: int) -> TrainState:
            with span("recovery", reason=reason):
                return _do_recovery(reason, epoch)

        def _do_recovery(reason: str, epoch: int) -> TrainState:
            """Roll back to the last checkpoint (else the initial snapshot),
            back the LR off, and return the state to continue from. The batch
            stream is NOT rewound — recovery moves forward through the data."""
            nonlocal restarts, consecutive_bad, step_base
            nonlocal pending_health, last_grad_norm
            restarts += 1
            consecutive_bad = 0
            step_base = None  # state.step jumps backward: refetch the base
            # the discarded trajectory's records must not be attributed to the
            # restored one: drop the un-emitted health record, the last grad
            # norm, and the watcher's EWMA baseline (pre-blowup norms resume)
            pending_health, last_grad_norm, self.last_health = None, None, None
            if health_watcher is not None:
                health_watcher.reset()
            if restarts > recovery.max_restarts:
                emit("on_recovery", epoch=epoch, reason=reason, restarts=restarts,
                     exhausted=True)
                # this raise skips on_fit_end: persist the trace NOW — the
                # rollback timeline is exactly what diagnosing this run needs
                finish_trace()
                msg = (
                    f"RecoveryPolicy budget exhausted: {restarts - 1} restarts "
                    f"(max_restarts={recovery.max_restarts}) did not stabilize "
                    f"the run (last trigger: {reason})"
                )
                raise RuntimeError(msg)
            target = (
                checkpoint_manager.latest_step() if checkpoint_manager is not None else None
            )
            if target is not None:
                restored = checkpoint_manager.restore(state, step=target)
                new_state = _place_tree(
                    restored, jax.tree.map(self._template_sharding, state)
                )
            else:
                new_state = jax.tree.map(lambda x: x.copy(), initial_snapshot)
            self._set_lr_scale(self._lr_scale * recovery.lr_backoff)
            logger.warning(
                "recovery %d/%d (%s): rolled back to %s, lr scale now %.3g",
                restarts, recovery.max_restarts, reason,
                f"checkpoint step {target}" if target is not None else "initial state",
                self._lr_scale,
            )
            emit("on_recovery", step=int(new_state.step), epoch=epoch, reason=reason,
                 restarts=restarts, restored_step=target, lr_scale=self._lr_scale)
            return new_state

        def save_mid_epoch(preempted: bool = False) -> None:
            # ONE position-stamping path for periodic and preemption saves:
            # resume reads the same metadata either way (epoch/n_steps are the
            # loop's live values at call time)
            extra: Dict[str, Any] = {"preempted": True} if preempted else {}
            if self._lr_scale != 1.0:  # recovery backoff survives the resume
                extra["lr_scale"] = self._lr_scale
            process_extra = None
            if cursor_source is not None:
                # the streaming batcher's exact position after n_steps batches
                # rides the sidecar, so resume SEEKS instead of rescanning;
                # cursors are recorded at produce time, so a prefetch/device-
                # feed stage reading ahead cannot outrun this lookup
                try:
                    cursor_meta = cursor_source.cursor_for(n_steps).to_metadata()
                except KeyError as exc:
                    logger.warning(
                        "stream cursor unavailable at step %d (%s); resume "
                        "will fall back to fast-forwarding the stream",
                        n_steps, exc,
                    )
                else:
                    extra["stream_cursor"] = cursor_meta
                    if jax.process_count() > 1:
                        # the shared sidecar has one writer (process 0), but
                        # every process streams its OWN disjoint shard: each
                        # rank's cursor rides its private per-process sidecar
                        process_extra = {"stream_cursor": cursor_meta}
            with span("checkpoint"):
                checkpoint_manager.save(
                    int(state.step),
                    state,
                    history=self.history,
                    metadata={
                        "mid_epoch": True,
                        "epoch": epoch,
                        "step_in_epoch": n_steps,
                        **extra,
                    },
                    process_metadata=process_extra,
                )
            emit("on_checkpoint", step=int(state.step), epoch=epoch,
                 mid_epoch=True, step_in_epoch=n_steps, **extra)

        install_preemption = (
            handle_preemption
            if handle_preemption is not None
            else checkpoint_manager is not None
        )
        preemption = PreemptionHandler() if install_preemption else None

        telemetry = StepTelemetry(warmup_steps=1)
        memory = MemoryMonitor()
        lr_schedule = (
            self.optimizer.scheduler.create(self.optimizer.learning_rate)
            if self.optimizer.scheduler is not None
            else None
        )

        def current_lr(step: int) -> float:
            # _lr_scale read at call time: recovery backoff shows up immediately
            # (every schedule kind is linear in its peak rate, so scaling the
            # schedule value equals rebuilding the schedule from the scaled lr)
            if lr_schedule is None:
                return float(self.optimizer.learning_rate) * self._lr_scale
            return float(lr_schedule(step)) * self._lr_scale

        def fit_end_payload() -> Dict[str, Any]:
            nonlocal profile_active
            payload = {
                "telemetry": telemetry.summary(),
                "compile": self.compile_tracker.report(),
                "peak_memory_bytes": memory.peak_bytes(),
                "history_len": len(self.history),
            }
            if memory.observed_samples:
                # the chunk-boundary sampling window (scan path): THIS fit's
                # high-water mark, vs the allocator's process-lifetime peak
                payload["peak_memory_sampled_bytes"] = memory.observed_peak_bytes
                payload["peak_memory_samples"] = memory.observed_samples
            if state is not None:  # sentinel-skipped updates over the run
                payload["bad_steps"] = int(state.bad_steps)
            input_record = input_summary()
            if input_record is not None:
                # cumulative feed efficiency: real vs grid tokens and the
                # steady effective-tokens/s (report renders, --compare gates)
                payload["input"] = input_record
            if tracing:
                # mirror the span layer into the event stream: whole-fit
                # goodput + THIS fit's per-span totals ride the terminal event
                payload["goodput"] = trace_window(fit_trace_base, fit_trace_t0)
                payload["spans"] = fit_spans()
            if profile_capture_dir is not None:
                if profile_active:
                    # a window still open (fit ended/preempted inside it):
                    # finalize the capture so the attribution reads real data
                    profile_stack.close()
                    profile_active = False
                # per-scope DEVICE-time attribution + per-program roofline
                # (obs.profile / obs.roofline) — the on-chip half of the
                # goodput story, joined against this fit's compiled programs
                payload.update(self._profile_payload(profile_capture_dir))
            return payload

        emit(
            "on_fit_start",
            epoch=start_epoch,
            epochs=epochs,
            model=type(self.model).__name__,
            loss=type(self.loss).__name__,
            optimizer=self.optimizer.name,
            learning_rate=self.optimizer.learning_rate,
            mesh={axis: int(n) for axis, n in self.mesh.shape.items()},
            sharding_rules=self.sharding_rules.describe(),
            resumed=bool(resume and pending_restore_step is not None),
            **(self.precision.describe() if self.precision is not None else {}),
        )

        if profile_steps is not None:
            profile_start, profile_stop = int(profile_steps[0]), int(profile_steps[1])
            if profile_stop <= profile_start or profile_start < 0:
                msg = f"profile_steps must be a valid [start, stop) window, got {profile_steps}"
                raise ValueError(msg)

            def resolved_profile_dir() -> str:
                if profile_dir is not None:
                    return profile_dir
                queue = list(explicit_loggers)
                while queue:  # MultiLogger nests sinks: search them too
                    sink = queue.pop(0)
                    if isinstance(sink, JsonlLogger):
                        return os.path.join(sink.run_dir, "profile")
                    if isinstance(sink, MultiLogger):
                        queue.extend(sink.loggers)
                return "jax_profile"

        profile_stack = contextlib.ExitStack()
        profile_active = False
        profile_capture_dir: Optional[str] = None  # set when a window opens
        measured_total = 0  # steps actually executed by THIS fit call
        last_emitted_at = 0
        step_base = None  # int(state.step) fetched once; then tracked on host
        # effective-token accounting (docs/performance.md "Feeding the
        # beast"): real (non-padding, valid-row) vs grid tokens fed to the
        # device — the padding-waste number sequence packing exists to move
        tokens_real_total = 0
        tokens_grid_total = 0
        tick_tokens_real = 0
        tick_tokens_grid = 0

        def count_tokens(batch: Batch) -> None:
            nonlocal tokens_real_total, tokens_grid_total
            mask = batch.get(self.padding_mask_field)
            if mask is None or getattr(mask, "ndim", 0) != 2:
                return
            mask = np.asarray(mask)
            valid = batch.get("valid")
            if valid is not None:
                real = int(mask[np.asarray(valid)].sum())
            else:
                real = int(mask.sum())
            tokens_real_total += real
            tokens_grid_total += mask.size

        def input_summary() -> Optional[Dict[str, float]]:
            if not tokens_grid_total:
                return None
            steady = telemetry.summary()
            steps_per_sec = steady.get("steps_per_sec")
            tokens_per_step = tokens_real_total / max(measured_total, 1)
            effective = (
                tokens_per_step * steps_per_sec
                if steps_per_sec is not None and math.isfinite(steps_per_sec)
                else float("nan")
            )
            return {
                "tokens_real": tokens_real_total,
                "tokens_grid": tokens_grid_total,
                "padding_fraction": 1.0 - tokens_real_total / tokens_grid_total,
                "effective_tokens_per_sec": effective,
            }

        def telemetry_tick(batch: Batch) -> Dict[str, float]:
            """Fold the steps since the last tick into the telemetry window
            (shared by the per-step emit path and the epoch-tail flush)."""
            nonlocal last_emitted_at, tick_tokens_real, tick_tokens_grid
            delta = measured_total - last_emitted_at
            last_emitted_at = measured_total
            reference = batch.get(self.padding_mask_field)
            rows = (
                int(np.asarray(reference).shape[0]) if reference is not None else None
            )
            tick = telemetry.tick(samples=rows * delta if rows else None, steps=delta)
            window_real = tokens_real_total - tick_tokens_real
            window_grid = tokens_grid_total - tick_tokens_grid
            tick_tokens_real, tick_tokens_grid = tokens_real_total, tokens_grid_total
            nan = float("nan")
            tick["padding_fraction"] = (
                1.0 - window_real / window_grid if window_grid else nan
            )
            tick["effective_tokens_per_sec"] = (
                window_real / delta * tick["steps_per_sec"] if delta else nan
            )
            return tick

        if pending_restore_step is not None and start_epoch >= epochs:
            # run already complete: restore the checkpoint and return it instead
            # of raising "received no batches" — the monitored best when one is
            # marked (what the uninterrupted fit returned), latest otherwise
            first = next(iter(batches_for(0)), None)
            if first is None:
                msg = "fit() received no batches"
                raise ValueError(msg)
            template = self.init_state(first)
            restore_step = pending_restore_step
            if monitor is not None and resumed_best_step is not None:
                restore_step = resumed_best_step
            restored = checkpoint_manager.restore(template, step=restore_step)
            logger.info("resume: run already complete at step %d", restore_step)
            emit("on_fit_end", step=restore_step, epoch=start_epoch,
                 note="resume: run already complete", **fit_end_payload())
            return _place_tree(restored, jax.tree.map(self._template_sharding, template))

        def account_step(
            batch: Batch,
            loss_value,
            step_metrics: Mapping[str, Any],
            epoch: int,
            step_id: Optional[int] = None,
            bad_total: Optional[int] = None,
            on_host: bool = False,
        ) -> bool:
            """Post-execution bookkeeping for ONE optimizer step — epoch
            loss/sentinel accumulation, health fetch + watcher, anomaly
            events, profiler-window close, per-step event emission — shared
            verbatim by the per-step loop and the scan fan-out. The fan-out
            passes host numpy metrics (``on_host=True``; the chunk's [K]
            arrays were already fetched in one sync) plus explicit
            ``step_id``/``bad_total``, because ``state.step``/``bad_steps``
            already sit at the chunk END during fan-out. Returns True when a
            recovery rollback fired, so a chunked caller discards the rest of
            its chunk's pre-rollback steps.
            """
            nonlocal epoch_loss, epoch_good, n_steps, measured_total
            nonlocal last_grad_norm, pending_health, consecutive_bad, step_base
            nonlocal state, profile_active
            rolled_back = False
            good = step_metrics["good"]
            if on_host:
                # same IEEE f32 adds as the device accumulation below, on the
                # already-fetched values — bitwise-identical epoch averages
                safe_loss = np.float32(loss_value) if bool(good) else np.float32(0.0)
                good_flag = np.int32(bool(good))
                if epoch_loss is not None and not isinstance(epoch_loss, np.generic):
                    # an interleaved device-accumulated step (health single
                    # step) made the accumulator a device scalar: fold it back
                    # to host ONCE — its value is already fenced by that
                    # step's health fetch — so the chunk fan-out below never
                    # dispatches K tiny device adds per chunk
                    epoch_loss = np.float32(epoch_loss)
                    epoch_good = np.int32(epoch_good)
            else:
                # accumulate on device: float() here would sync every step.
                # Sentinel-skipped steps contribute 0 (their loss is
                # non-finite and would poison the epoch average).
                safe_loss = jnp.where(good, loss_value, 0.0)
                good_flag = good.astype(jnp.int32)
            epoch_loss = safe_loss if epoch_loss is None else epoch_loss + safe_loss
            epoch_good = good_flag if epoch_good is None else epoch_good + good_flag
            n_steps += 1
            measured_total += 1
            count_tokens(batch)
            last_grad_norm = step_metrics["grad_norm"]
            if (
                health_cfg is not None
                and "health" in step_metrics
                and measured_total % health_cfg.cadence == 0
            ):
                # THE health sync: one device_get of the small health
                # pytree — it blocks on the step's outputs, so the
                # record is loss-fenced like a StepTelemetry tick
                fetched = jax.device_get(step_metrics["health"])
                health_record = jax.tree.map(
                    lambda x: x.tolist() if getattr(x, "ndim", 0) else float(x),
                    fetched,
                )
                self.last_health = health_record
                pending_health = health_record
                if health_watcher is not None:
                    warning = health_watcher.observe(health_record)
                    if warning is not None:
                        if step_base is None:
                            step_base = int(state.step) - measured_total
                        emit(
                            "on_health_warning",
                            step=step_base + measured_total,
                            epoch=epoch,
                            **warning,
                        )
                        if health_watcher.trigger_recovery and recovery is not None:
                            state = do_recovery("health_warning", epoch)
                            epoch_loss, epoch_good = None, None
                            rolled_back = True
            if check_anomalies or recovery is not None:
                # a recovery policy must see every bad step even when
                # detect_anomalies=False silenced the event emission
                if not bool(step_metrics["good"]):
                    consecutive_bad += 1
                    if check_anomalies:
                        emit(
                            "on_anomaly",
                            step=int(state.step) if step_id is None else step_id,
                            epoch=epoch,
                            loss=float(loss_value),
                            grad_norm=float(step_metrics["grad_norm"]),
                            consecutive_bad=consecutive_bad,
                            bad_steps_total=(
                                int(state.bad_steps) if bad_total is None else bad_total
                            ),
                        )
                    if (
                        recovery is not None
                        and consecutive_bad >= recovery.max_consecutive_bad
                    ):
                        state = do_recovery("consecutive_bad_steps", epoch)
                        # the epoch average must describe the RESTORED
                        # trajectory, not the discarded one
                        epoch_loss, epoch_good = None, None
                        rolled_back = True
                else:
                    consecutive_bad = 0
            if profile_active and measured_total >= profile_stop:
                profile_stack.close()
                profile_active = False
            if event_every and measured_total % event_every == 0:
                if step_base is None:
                    # one-time base fetch: state.step then advances in
                    # lockstep with measured_total within this fit
                    step_base = int(state.step) - measured_total
                emit_step = step_base + measured_total
                loss_f = float(loss_value)  # THE per-event device sync
                tick = telemetry_tick(batch)
                emit(
                    "on_train_step",
                    step=emit_step,
                    epoch=epoch,
                    loss=loss_f,
                    # the rate the optimizer APPLIED: optax schedules
                    # are indexed by steps completed before the update
                    lr=current_lr(emit_step - 1),
                    samples_per_sec=tick["samples_per_sec"],
                    steps_per_sec=tick["steps_per_sec"],
                    step_seconds=tick["step_seconds"],
                    # padding-waste telemetry: the feed-efficiency numbers
                    # packing/bucketing exist to move (obs gauges + SLOs)
                    effective_tokens_per_sec=tick["effective_tokens_per_sec"],
                    padding_fraction=tick["padding_fraction"],
                    # a health record fetched since the last emission
                    # rides the next step event (cadences may differ)
                    **({"health": pending_health} if pending_health is not None else {}),
                )
                pending_health = None
            return rolled_back

        stopped_early = False
        cursor_source = None  # the current epoch's resumable batch source
        # the per-epoch goodput window: opens here and RE-opens right after
        # each on_epoch_end, so the inter-epoch tail (the end-of-epoch
        # checkpoint save, best tracking) lands in the NEXT epoch's window —
        # consecutive windows tile the fit wall-clock with no gaps
        epoch_trace_base = trace.snapshot(only_current_thread=True) if tracing else {}
        epoch_trace_t0 = time.perf_counter()
        # profile_stack closes a still-open profiler window on any exit; the
        # preemption handler restores the previous SIGTERM/SIGINT handlers
        with profile_stack, (preemption or contextlib.nullcontext()):
            for epoch in range(start_epoch, epochs):
                # n_steps = position in the epoch's batch stream (skipped batches
                # included, keeping checkpoint_every aligned across resumes);
                # epoch_good = device count of batches that actually trained AND
                # passed the sentinel on THIS process
                epoch_loss, epoch_good, n_steps = None, None, 0
                skipped = 0
                last_batch = None
                epoch_needs_mark = True  # re-mark per epoch: discounts the
                # inter-epoch validation/checkpoint gap from the telemetry window
                epoch_batches = batches_for(epoch)
                cursor_source = (
                    epoch_batches
                    if getattr(epoch_batches, "supports_cursor", False)
                    else None
                )
                if (
                    pending_stream_cursor is not None
                    and epoch == start_epoch
                    and cursor_source is not None
                ):
                    recorded = int(pending_stream_cursor.get("batches", -1))
                    if recorded == skip_steps:
                        # seek: the batcher resumes mid-epoch bit-for-bit
                        # without re-reading the skipped slabs
                        cursor_source.restore_cursor(pending_stream_cursor)
                        skipped = skip_steps  # nothing left to consume-and-drop
                        n_steps = skip_steps
                    else:
                        logger.warning(
                            "stream cursor records %d batches but the "
                            "checkpoint position is %d; falling back to "
                            "fast-forward", recorded, skip_steps,
                        )
                    pending_stream_cursor = None
                if scan_chunk:
                    # a factory callable hid its batcher from the fit-start
                    # check: reject what it actually returned, before any
                    # step of this epoch runs
                    reject_bucketed(epoch_batches)
                if prefetch:
                    from replay_tpu.data.nn.prefetch import prefetch as _prefetch

                    epoch_batches = _prefetch(iter(epoch_batches), depth=prefetch)
                if tracing and not scan_chunk:
                    # times every next() as data_wait — i.e. what the prefetch
                    # queue could NOT hide from the step loop. (Chunked, the
                    # stream is consumed on the FEEDER thread; the fit
                    # thread's data_wait is its wait on the feed, below.)
                    epoch_batches = traced_iterator(epoch_batches, trace)
                if scan_chunk:
                    # ---- scan-chunked epoch: K steps per XLA dispatch, fed by
                    # a device-prefetch stage (docs/performance.md "Closing
                    # the dispatch gap") -------------------------------------
                    from replay_tpu.data.nn.prefetch import DevicePrefetcher

                    batch_iter = iter(epoch_batches)
                    first_batch = None
                    for batch in batch_iter:
                        # the per-step loop's per-batch preamble (state init /
                        # restore / recovery snapshot / resume fast-forward),
                        # run on the fit thread BEFORE the feeder takes over
                        if state is None:
                            state = self.init_state(batch)
                            if pending_restore_step is not None:
                                restored = checkpoint_manager.restore(
                                    state, step=pending_restore_step
                                )
                                state = _place_tree(
                                    restored, jax.tree.map(self._template_sharding, state)
                                )
                                pending_restore_step = None
                        if recovery is not None and initial_snapshot is None:
                            # rollback target until the first checkpoint lands;
                            # .copy() detaches from the donation chain
                            initial_snapshot = jax.tree.map(lambda x: x.copy(), state)
                        if epoch == start_epoch and skipped < skip_steps:
                            skipped += 1
                            n_steps += 1
                            continue
                        first_batch = batch
                        break
                    if first_batch is not None:
                        stream = itertools.chain([first_batch], batch_iter)
                        items = _chunk_schedule(
                            stream, scan_chunk, health_every, start=measured_total
                        )
                        feed = (
                            DevicePrefetcher(items, self._chunk_placer(trace), depth=1)
                            if device_feed
                            # feed off: items pass through unplaced and the
                            # scan branch below places them on the FIT thread
                            # (h2d lands in the goodput fractions — the A/B
                            # shows exactly what the feed would have hidden)
                            else ((item, None) for item in items)
                        )
                        feed_stream = traced_iterator(feed, trace) if tracing else feed
                        try:
                            for item, placed in feed_stream:
                                if epoch_needs_mark:
                                    telemetry.mark()
                                    epoch_needs_mark = False
                                kind, payload = item
                                steps_before = n_steps
                                if kind == "step":
                                    # health-cadence / short-tail single step
                                    # through the existing per-step program
                                    # (the health-instrumented variant when a
                                    # HealthConfig is attached)
                                    if (
                                        profile_steps is not None
                                        and not profile_active
                                        and measured_total == profile_start
                                    ):
                                        from replay_tpu.utils.profiling import (
                                            trace as _profiler_trace,
                                        )

                                        profile_capture_dir = resolved_profile_dir()
                                        profile_stack.enter_context(
                                            _profiler_trace(profile_capture_dir)
                                        )
                                        profile_active = True
                                    state, loss_value = self.traced_train_step(
                                        state, payload
                                    )
                                    account_step(
                                        payload, loss_value, self.last_step_metrics, epoch
                                    )
                                    last_batch = payload
                                else:  # "scan": K optimizer steps in ONE dispatch
                                    chunk = payload
                                    k = len(chunk)
                                    if (
                                        profile_steps is not None
                                        and not profile_active
                                        and measured_total <= profile_start < measured_total + k
                                    ):
                                        # the window rounds out to chunk boundaries
                                        from replay_tpu.utils.profiling import (
                                            trace as _profiler_trace,
                                        )

                                        profile_capture_dir = resolved_profile_dir()
                                        profile_stack.enter_context(
                                            _profiler_trace(profile_capture_dir)
                                        )
                                        profile_active = True
                                    scan_fn = self._ensure_train_scan()
                                    if placed is None:
                                        # device_feed=False: synchronous
                                        # stack + placement on the fit thread
                                        with self._h2d_span():
                                            placed = self._put_stacked(
                                                self._stack_chunk(chunk)
                                            )
                                    compile_before = (
                                        self.compile_tracker.total_compile_seconds
                                    )
                                    span_cm = (
                                        trace.span("train_step", steps=k)
                                        if tracing
                                        else contextlib.nullcontext()
                                    )
                                    self._record_template(
                                        "train_scan", scan_fn, state, placed
                                    )
                                    with span_cm as step_span:
                                        with self.compile_tracker.observe("train_scan"):
                                            state, chunk_metrics = scan_fn(state, placed)
                                        # ONE host sync per chunk: the [K]
                                        # per-step metrics fence the span and
                                        # feed the fan-out accounting below
                                        losses = np.asarray(chunk_metrics["loss"])
                                        goods = np.asarray(chunk_metrics["good"])
                                        grad_norms = np.asarray(chunk_metrics["grad_norm"])
                                    if tracing:
                                        compile_delta = (
                                            self.compile_tracker.total_compile_seconds
                                            - compile_before
                                        )
                                        if compile_delta > 0:
                                            trace.carve(step_span, "compile", compile_delta)
                                    self.last_step_metrics = chunk_metrics
                                    # chunk-boundary HBM sample: the scan path
                                    # otherwise only snapshots memory per
                                    # epoch; a CPU backend (no allocator
                                    # stats) makes this a no-op
                                    memory.observe()
                                    if step_base is None:
                                        # state.step already sits at the chunk END
                                        step_base = int(state.step) - (measured_total + k)
                                    bad_in_chunk = np.cumsum(~goods)
                                    bad_before = None
                                    if (
                                        check_anomalies or recovery is not None
                                    ) and bad_in_chunk[-1]:
                                        bad_before = int(state.bad_steps) - int(
                                            bad_in_chunk[-1]
                                        )
                                    for i in range(k):
                                        rolled_back = account_step(
                                            chunk[i],
                                            losses[i],
                                            {
                                                "loss": losses[i],
                                                "good": goods[i],
                                                "grad_norm": grad_norms[i],
                                            },
                                            epoch,
                                            step_id=step_base + measured_total + 1,
                                            bad_total=(
                                                bad_before + int(bad_in_chunk[i])
                                                if bad_before is not None
                                                else None
                                            ),
                                            on_host=True,
                                        )
                                        if rolled_back:
                                            # the rest of the chunk belongs to
                                            # the DISCARDED trajectory: its
                                            # batches stay consumed (the stream
                                            # position advances, keeping
                                            # checkpoint/resume alignment) but
                                            # are not accounted
                                            n_steps += k - (i + 1)
                                            measured_total += k - (i + 1)
                                            break
                                    last_batch = chunk[-1]
                                boundary_saved = False
                                if (
                                    checkpoint_every
                                    and checkpoint_manager is not None
                                    and n_steps // checkpoint_every
                                    > steps_before // checkpoint_every
                                ):
                                    # a checkpoint_every boundary crossed INSIDE
                                    # the chunk saves once at the chunk end —
                                    # the only point this state exists; the
                                    # recorded position is the current n_steps
                                    save_mid_epoch()
                                    boundary_saved = True
                                if preemption is not None and preemption.requested:
                                    # chunk-boundary preemption exit (same
                                    # contract as the per-step path); the
                                    # event — and the trace flush it carries —
                                    # lands BEFORE the shutdown-window save,
                                    # so a save that dies cannot take the
                                    # span tree with it
                                    emit("on_preemption", step=int(state.step),
                                         epoch=epoch, signal=preemption.signal_name)
                                    if checkpoint_manager is not None and not boundary_saved:
                                        save_mid_epoch(preempted=True)
                                    logger.warning(
                                        "preemption: checkpoint saved at step %d; "
                                        "exiting fit",
                                        int(state.step),
                                    )
                                    emit("on_fit_end", step=int(state.step),
                                         epoch=epoch, preempted=True,
                                         **fit_end_payload())
                                    return state
                        finally:
                            if isinstance(feed, DevicePrefetcher):
                                feed.close()
                    epoch_batches = ()  # the per-step loop below is skipped
                for batch in epoch_batches:
                    if state is None:
                        state = self.init_state(batch)
                        if pending_restore_step is not None:
                            restored = checkpoint_manager.restore(
                                state, step=pending_restore_step
                            )
                            state = _place_tree(
                                restored, jax.tree.map(self._template_sharding, state)
                            )
                            pending_restore_step = None
                    if recovery is not None and initial_snapshot is None:
                        # rollback target until the first checkpoint lands;
                        # .copy() detaches from the donation chain
                        initial_snapshot = jax.tree.map(lambda x: x.copy(), state)
                    if epoch == start_epoch and skipped < skip_steps:
                        # fast-forward: the batch stream is deterministic per epoch,
                        # so consuming without stepping lands on the exact position
                        skipped += 1
                        n_steps += 1
                        continue
                    if epoch_needs_mark:
                        telemetry.mark()
                        epoch_needs_mark = False
                    if (
                        profile_steps is not None
                        and not profile_active
                        and measured_total == profile_start
                    ):
                        # aliased: `trace` is the fit-scope Tracer handle
                        from replay_tpu.utils.profiling import trace as _profiler_trace

                        profile_capture_dir = resolved_profile_dir()
                        profile_stack.enter_context(_profiler_trace(profile_capture_dir))
                        profile_active = True
                    # traced: loss-fenced span + compile carve; untraced: the
                    # plain async-dispatch step
                    state, loss_value = self.traced_train_step(state, batch)
                    account_step(batch, loss_value, self.last_step_metrics, epoch)
                    last_batch = batch
                    boundary_saved = False
                    if (
                        checkpoint_every
                        and checkpoint_manager is not None
                        and n_steps % checkpoint_every == 0
                    ):
                        save_mid_epoch()
                        boundary_saved = True
                    if preemption is not None and preemption.requested:
                        # the signal handler only set a flag; this is the step
                        # boundary it asked for — save a position-stamped
                        # checkpoint and exit cleanly (resume=True continues
                        # from this exact batch). A periodic save that just
                        # landed on this same step already recorded the
                        # position — don't serialize the state twice in the
                        # shutdown window.
                        emit("on_preemption", step=int(state.step), epoch=epoch,
                             signal=preemption.signal_name)
                        if checkpoint_manager is not None and not boundary_saved:
                            save_mid_epoch(preempted=True)
                        logger.warning(
                            "preemption: checkpoint saved at step %d; exiting fit",
                            int(state.step),
                        )
                        emit("on_fit_end", step=int(state.step), epoch=epoch,
                             preempted=True, **fit_end_payload())
                        return state
                # a resumed epoch averages only the steps THIS process ran, and
                # the average runs over sentinel-approved steps only (skipped
                # steps contributed 0 loss); NaN when nothing was measured or
                # every measured step was bad
                good_count = int(epoch_good) if epoch_good is not None else 0
                record = {
                    "epoch": epoch,
                    "train_loss": (
                        float(epoch_loss) / good_count if good_count else float("nan")
                    ),
                }
                if event_every and measured_total > last_emitted_at and last_batch is not None:
                    # flush the tail steps into the telemetry window HERE —
                    # float(epoch_loss) above already fenced them, and ticking
                    # after validation would dilute the steady-state rate;
                    # fits shorter than the event cadence get real numbers
                    telemetry_tick(last_batch)
                if val_batches is not None:
                    # several validation streams (the reference's sequential
                    # CombinedLoader): a dict of factories gets per-stream prefixes
                    streams = (
                        val_batches if isinstance(val_batches, dict) else {"": val_batches}
                    )
                    with span("validation"):
                        for stream_name, factory in streams.items():
                            stream_metrics = self.validate(
                                state,
                                factory(),
                                metrics=metrics,
                                top_k=top_k,
                                item_count=item_count,
                                postprocessors=postprocessors,
                            )
                            prefix = f"{stream_name}/" if stream_name else ""
                            record.update(
                                {f"{prefix}{k}": v for k, v in stream_metrics.items()}
                            )
                    emit("on_validation_end",
                         step=int(state.step) if state is not None else None,
                         epoch=epoch, record=record)
                self.history.append(record)
                epoch_payload: Dict[str, Any] = {"record": record}
                if state is not None:
                    # reliability rollups: obs.report --compare gates on the
                    # cumulative sentinel count, not just throughput/MFU
                    epoch_payload["bad_steps"] = int(state.bad_steps)
                if last_grad_norm is not None:
                    # the last executed step's global grad norm (one scalar
                    # sync per epoch; non-finite serializes as JSON null)
                    epoch_payload["grad_norm"] = float(last_grad_norm)
                input_record = input_summary()
                if input_record is not None:  # cumulative feed efficiency
                    epoch_payload["input"] = input_record
                if health_cfg is not None and self.last_health is not None:
                    epoch_payload["health"] = self.last_health
                if tracing:
                    # the goodput contract: phase fractions over this epoch's
                    # wall clock, summing to 1.0 (docs/performance.md)
                    epoch_payload["goodput"] = trace_window(
                        epoch_trace_base, epoch_trace_t0
                    )
                    # re-open the window HERE: what follows (this epoch's
                    # checkpoint save, best tracking) bills to the next epoch
                    epoch_trace_base = trace.snapshot(only_current_thread=True)
                    epoch_trace_t0 = time.perf_counter()
                emit("on_epoch_end",
                     step=int(state.step) if state is not None else None,
                     epoch=epoch, **epoch_payload)
                if not log_every:
                    # log_every=0 silences the per-step prints only — the
                    # per-epoch record line predates the event layer and stays
                    logger.info("epoch %d: %s", epoch, record)

                if (
                    recovery is not None
                    and monitor is not None
                    and monitor in record
                    # epoch_good is None when nothing fed the average — a
                    # fully-fast-forwarded resumed epoch, or a mid-epoch
                    # rollback that already answered this incident (the reset
                    # above) — so the NaN record must not burn a second restart
                    and epoch_good is not None
                ):
                    # epoch-level blowup guard: the monitored value went
                    # non-finite, or worsened past blowup_factor x the best —
                    # roll back BEFORE this epoch's checkpoint could become the
                    # rollback target, and skip its best-tracking entirely
                    value = float(record[monitor])
                    blown = not math.isfinite(value)
                    if (
                        not blown
                        and recovery.blowup_factor is not None
                        and best_value is not None
                        and math.isfinite(best_value)
                    ):
                        blown = (
                            value > best_value * recovery.blowup_factor
                            if mode == "min"
                            else value < best_value / recovery.blowup_factor
                        )
                    if blown:
                        state = do_recovery("metric_blowup", epoch)
                        continue

                improved = False
                if monitor is not None:
                    if monitor not in record:
                        msg = f"monitor '{monitor}' not in the epoch record {sorted(record)}"
                        raise KeyError(msg)
                    value = record[monitor]
                    improved = (
                        best_value is None
                        or (mode == "max" and value > best_value)
                        or (mode == "min" and value < best_value)
                    )
                    if improved:
                        # deep-copy: the NEXT train_step donates this state's buffers
                        # (donate_argnums=0), which would leave a dead pytree here
                        best_state = jax.tree.map(lambda x: x.copy(), state)
                        best_value, stale_epochs = value, 0
                    else:
                        stale_epochs += 1
                if checkpoint_manager is not None and state is not None:
                    metadata = {"epoch": epoch}
                    if self._lr_scale != 1.0:  # recovery backoff survives resume
                        metadata["lr_scale"] = self._lr_scale
                    if monitor:
                        metadata.update({"best": improved, monitor: value})
                    with span("checkpoint"):
                        checkpoint_manager.save(
                            int(state.step),
                            state,
                            history=self.history,
                            metadata=metadata,
                        )
                        if improved:
                            checkpoint_manager.mark_best(int(state.step))
                    emit("on_checkpoint", step=int(state.step), epoch=epoch,
                         mid_epoch=False, best=bool(improved) if monitor else None)
                if monitor is not None and patience is not None and stale_epochs >= patience:
                    logger.info(
                        "early stop: no %s improvement for %d epochs", monitor, patience
                    )
                    stopped_early = True
                    break
        if state is None:
            msg = "fit() received no batches"
            raise ValueError(msg)
        if best_state is None and resumed_best_step is not None and monitor is not None:
            # no post-resume epoch beat the pre-kill best: return THAT state,
            # exactly as the uninterrupted run would have
            restored = checkpoint_manager.restore(state, step=resumed_best_step)
            best_state = _place_tree(
                restored, jax.tree.map(self._template_sharding, state)
            )
        emit("on_fit_end", step=int(state.step), stopped_early=stopped_early,
             **fit_end_payload())
        return best_state if best_state is not None else state

    # the public entry is the thin exception-safe wrapper above; its help()
    # should read as the real thing
    fit.__doc__ = _fit_impl.__doc__

    # -- eval / predict ---------------------------------------------------- #
    def _build_eval_logits(self):
        model = self.model

        def eval_logits(params, batch: Batch, candidates: Optional[jnp.ndarray]):
            kwargs = {name: batch[name] for name in self._inference_params if name in batch}
            return model.apply(
                {"params": params},
                **kwargs,
                candidates_to_score=candidates,
                method=type(model).forward_inference,
            )

        return jax.jit(self.compile_tracker.wrap(self._scoped(eval_logits), "eval_logits"))

    def predict_logits(
        self, state: TrainState, batch: Batch, candidates: Optional[jnp.ndarray] = None
    ) -> jnp.ndarray:
        """Next-item logits [B, num_items] (or [B, K] for candidates)."""
        if self._eval_logits is None:
            self._eval_logits = self._build_eval_logits()
        return self._eval_logits(state.params, self._put_batch(batch), candidates)

    # -- eval-time catalog cache (TwoTower-style item towers) --------------- #
    def _precompute_catalog(self, state: TrainState, batch: Batch):
        """Encode the whole catalog ONCE per evaluation pass when the model has
        an item tower (the reference ItemTower's eval cache, invalidated by
        training simply because each validate/predict call recomputes it)."""
        model = self.model
        if not hasattr(type(model), "encode_items"):
            return None
        if self._catalog_fn is None:
            self._catalog_fn = jax.jit(
                self.compile_tracker.wrap(
                    self._scoped(
                        lambda params, features: model.apply(
                            {"params": params},
                            item_feature_tensors=features,
                            method=type(model).encode_items,
                        )
                    ),
                    "encode_items",
                )
            )
        return self._catalog_fn(state.params, batch.get("item_feature_tensors"))

    def _get_query_embeddings_fn(self):
        model = self.model
        if self._query_embeddings_fn is None:

            def embed(params, feature_tensors, padding_mask):
                return model.apply(
                    {"params": params},
                    feature_tensors,
                    padding_mask,
                    method=type(model).get_query_embeddings,
                )

            self._query_embeddings_fn = jax.jit(
                self.compile_tracker.wrap(self._scoped(embed), "query_embeddings")
            )
        return self._query_embeddings_fn

    def _catalog_logits(self, state: TrainState, batch: Batch, catalog) -> jnp.ndarray:
        """Score query embeddings against precomputed catalog embeddings."""
        batch = self._put_batch(batch)
        queries = self._get_query_embeddings_fn()(
            state.params, batch[self.feature_field], batch[self.padding_mask_field]
        )
        return queries @ catalog.T

    def validate(
        self,
        state: TrainState,
        batches: Iterable[Batch],
        metrics: Sequence[str] = ("ndcg", "recall", "map"),
        top_k: Sequence[int] = (1, 5, 10),
        item_count: Optional[int] = None,
        postprocessors: Sequence[Callable] = (),
    ) -> Mapping[str, float]:
        """Top-k metrics over validation batches (ground_truth/train padded with
        −1, per MetricsBuilder's contract)."""
        import itertools

        builder = MetricsBuilder(metrics=metrics, top_k=top_k, item_count=item_count)
        max_k = builder.max_k
        iterator = iter(batches)
        try:
            first = next(iterator)
        except StopIteration:
            return builder.get_metrics()
        catalog = self._precompute_catalog(state, first)
        for batch in itertools.chain([first], iterator):
            if catalog is not None:
                logits = self._catalog_logits(state, batch, catalog)
            else:
                logits = self.predict_logits(state, batch)
            for post in postprocessors:
                logits = post(logits, batch)
            _, top_ids = jax.lax.top_k(logits, max_k)
            builder.add_prediction(
                _local_rows(top_ids), batch["ground_truth"], batch.get("train"),
                batch.get("valid"),
            )
        if jax.process_count() > 1:
            # every host accumulated only ITS shard: sum the (psum-able) states
            # across hosts — the reference's sync_dist=True reduction
            from jax.experimental import multihost_utils

            gathered = multihost_utils.process_allgather(builder.state())
            builder.load_state(jax.tree.map(lambda x: np.asarray(x).sum(axis=0), gathered))
        return builder.get_metrics()

    def predict_top_k(
        self,
        state: TrainState,
        batches: Iterable[Batch],
        k: int,
        postprocessors: Sequence[Callable] = (),
        candidates: Optional[jnp.ndarray] = None,
        query_id_field: str = "query_id",
    ):
        """Top-k recommendations as (query_ids, item_ids, scores) numpy arrays.

        The per-batch path mirrors the reference predictions callback
        (replay/nn/lightning/callback/predictions_callback.py:81-108): score →
        postprocess → top-k → accumulate; candidate ids are mapped back to
        catalog ids when ``candidates`` is given.
        """
        import itertools

        if isinstance(batches, Mapping):  # a single batch: iterating it would
            batches = [batches]  # silently yield its string keys
        all_queries, all_items, all_scores = [], [], []
        iterator = iter(batches)
        try:
            first = next(iterator)
        except StopIteration:
            iterator, first = iter(()), None
        catalog = (
            self._precompute_catalog(state, first)
            if candidates is None and first is not None
            else None
        )
        batches = itertools.chain([first], iterator) if first is not None else iterator
        for batch in batches:
            if catalog is not None:
                logits = self._catalog_logits(state, batch, catalog)
            else:
                logits = self.predict_logits(state, batch, candidates)
            if candidates is not None:
                # visible to postprocessors (SeenItemsFilter's candidate matching)
                batch = {**batch, "candidates_to_score": jnp.asarray(candidates)}
            for post in postprocessors:
                logits = post(logits, batch)
            scores, top_idx = jax.lax.top_k(logits, k)
            if candidates is not None:
                top_ids = jnp.asarray(candidates)[top_idx]
            else:
                top_ids = top_idx
            valid = np.asarray(batch.get("valid", np.ones(top_ids.shape[0], bool)))
            all_items.append(np.asarray(top_ids)[valid])
            all_scores.append(np.asarray(scores)[valid])
            if query_id_field in batch:
                all_queries.append(np.asarray(batch[query_id_field])[valid])
        items = np.concatenate(all_items) if all_items else np.zeros((0, k), np.int32)
        scores = np.concatenate(all_scores) if all_scores else np.zeros((0, k), np.float32)
        queries = np.concatenate(all_queries) if all_queries else np.arange(items.shape[0])
        return queries, items, scores

    def predict_query_embeddings(self, state: TrainState, batches: Iterable[Batch]):
        """Last-position query embeddings [N, E] (the reference
        QueryEmbeddingsPredictionCallback), e.g. for two-stage features."""
        fn = self._get_query_embeddings_fn()
        chunks, queries = [], []
        for batch in batches:
            batch = self._put_batch(batch)
            embeddings = fn(state.params, batch[self.feature_field], batch[self.padding_mask_field])
            valid = np.asarray(batch.get("valid", np.ones(embeddings.shape[0], bool)))
            chunks.append(np.asarray(embeddings)[valid])
            if "query_id" in batch:
                queries.append(np.asarray(batch["query_id"])[valid])
        embeddings = np.concatenate(chunks) if chunks else np.zeros((0, 0))
        query_ids = np.concatenate(queries) if queries else np.arange(len(embeddings))
        return query_ids, embeddings

    def resize_vocabulary(
        self,
        state: TrainState,
        new_cardinality: int,
        init_tensor=None,
        carry_opt_state: bool = True,
        init: str = "mean",
        rng: Optional[jax.Array] = None,
    ) -> TrainState:
        """Catalog growth between — or DURING — retrains: item-table surgery
        with the optimizer moments resized in lockstep.

        ``carry_opt_state=True`` (default, the continual-training path) keeps
        every trained row's Adam moments and zero-initializes the cold rows'
        (``vocabulary.resize_optimizer_state``) so a mid-run grow neither
        crashes deep in optax nor silently resets the optimizer; ``False``
        restores the old between-retrains behavior (fresh ``tx.init`` state).
        ``init`` picks the cold-row warm start when no ``init_tensor`` is
        given: ``"mean"`` (the reference default) or ``"xavier"`` (the
        reference's expansion recipe, ``set_item_embeddings_by_size``).
        Step/rng carry over either way."""
        from replay_tpu.nn.vocabulary import (
            resize_item_embeddings,
            set_item_embeddings_by_size,
        )
        from replay_tpu.parallel.sharding import params_shardings

        host_params = jax.tree.map(np.asarray, state.params)
        host_opt = (
            jax.tree.map(np.asarray, state.opt_state) if carry_opt_state else None
        )
        if init == "xavier" and init_tensor is None:
            result = set_item_embeddings_by_size(
                host_params, self.model.schema, new_cardinality, rng=rng,
                opt_state=host_opt,
            )
        elif init == "mean" or init_tensor is not None:
            result = resize_item_embeddings(
                host_params, self.model.schema, new_cardinality, init_tensor,
                opt_state=host_opt,
            )
        else:
            msg = f"unknown init {init!r}: use 'mean' or 'xavier'"
            raise ValueError(msg)
        params, resized_opt = result if carry_opt_state else (result, None)
        shardings = params_shardings(self.mesh, params, self.sharding_rules)
        params = _place_tree(params, shardings)
        self._train_step = None  # shapes changed: retrace
        self._train_scan = None
        self._eval_logits = None
        self._query_embeddings_fn = None
        self._catalog_fn = None
        opt_state = self._tx.init(params)
        if carry_opt_state:
            # the fresh init is the SHAPE/placement template only: carried
            # host moments land leaf-by-leaf on its shardings (moments keep
            # their vocab sharding like a checkpoint restore would). Only
            # MESH shardings pin — uncommitted state scalars (Adam's count)
            # must stay free or the jitted step hits a device conflict
            def place(template, value):
                value = np.asarray(value)
                sharding = getattr(template, "sharding", None)
                if isinstance(sharding, NamedSharding):
                    return jax.device_put(value, sharding)
                return jnp.asarray(value)

            opt_state = jax.tree.map(place, opt_state, resized_opt)
        if jax.process_count() > 1:
            opt_state = _globalize_scalars(self.mesh, opt_state)
        return TrainState(
            step=state.step,
            params=params,
            opt_state=opt_state,
            rng=state.rng,
            bad_steps=state.bad_steps,
        )

    def finetune(
        self,
        state: TrainState,
        train_batches,
        new_cardinality: Optional[int] = None,
        init: str = "xavier",
        epochs: int = 1,
        **fit_kwargs,
    ) -> TrainState:
        """The continual-training entry (docs/robustness.md "Zero-downtime
        swaps and canary promotion"): optionally grow the catalog —
        optimizer-state-safe, xavier warm start for the cold rows — then fit
        from the given trained state on the fresh interaction tail. A thin,
        named seam so the promotion driver and the replay harness share one
        code path with plain ``fit``."""
        schema = self.model.schema
        feature_name = schema.item_id_feature_name
        if new_cardinality is not None and feature_name is not None:
            if new_cardinality < schema[feature_name].cardinality:
                msg = (
                    f"finetune cannot shrink the catalog "
                    f"({schema[feature_name].cardinality} -> {new_cardinality})"
                )
                raise ValueError(msg)
            if new_cardinality > schema[feature_name].cardinality:
                state = self.resize_vocabulary(
                    state, new_cardinality, carry_opt_state=True, init=init
                )
        return self.fit(train_batches, epochs=epochs, state=state, **fit_kwargs)

    def _set_lr_scale(self, scale: float) -> None:
        """Rebuild the optimizer with the base learning rate scaled by
        ``scale`` (RecoveryPolicy backoff). The optax state layout is identical
        for any LR, so a restored ``opt_state`` keeps working; the jitted step
        functions are invalidated (one retrace per rollback — rare by design)."""
        self._lr_scale = float(scale)
        factory = dataclasses.replace(
            self.optimizer, learning_rate=self.optimizer.learning_rate * self._lr_scale
        )
        self._tx = factory.create()
        self._train_step = None
        self._train_scan = None

    # -- checkpointing ------------------------------------------------------ #
    def save_checkpoint(
        self, path: str, state: TrainState, backend: Optional[str] = None
    ) -> None:
        """Write the full TrainState (params + optimizer + PRNG) to ``path``.

        ``backend=None`` defers to save_pytree's default: npz on one process,
        orbax under multi-host (npz would host-gather non-addressable leaves).
        """
        from replay_tpu.utils.checkpoint import save_pytree

        save_pytree(path, state, {"step": int(state.step)}, backend=backend)

    def restore_checkpoint(self, path: str, example_batch: Batch) -> TrainState:
        """Rebuild a TrainState from disk; the example batch supplies the template
        structure and the mesh shardings are re-applied on load."""
        from replay_tpu.utils.checkpoint import restore_pytree

        template = self.init_state(example_batch)
        restored = restore_pytree(path, template)
        shardings = jax.tree.map(self._template_sharding, template)
        return _place_tree(restored, shardings)

    def _template_sharding(self, target_leaf):
        # inherit the template's MESH sharding (params AND optimizer moments
        # keep their vocab sharding); other leaves replicate over the mesh
        sharding = getattr(target_leaf, "sharding", None)
        if not isinstance(sharding, NamedSharding):
            sharding = NamedSharding(self.mesh, P())
        return sharding

    def predict_dataframe(self, state, batches, k, **kwargs):
        """predict_top_k as a tidy (query_id, item_id, rating) pandas frame —
        the PandasTopItemsCallback equivalent."""
        import pandas as pd

        queries, items, scores = self.predict_top_k(state, batches, k, **kwargs)
        return pd.DataFrame(
            {
                "query_id": np.repeat(queries, k),
                "item_id": items.reshape(-1),
                "rating": scores.reshape(-1),
            }
        )
