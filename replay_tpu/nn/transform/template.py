"""Default per-split transform pipelines.

Capability parity with replay/nn/transform/template/{sasrec,twotower}.py: train =
next-token shift → rename masks → unsqueeze → group; val/test/predict = rename +
group. A BERT4Rec MLM template (token-mask based) covers the legacy masking path
(replay/models/nn/sequential/bert4rec/dataset.py:55).
"""

from __future__ import annotations

from typing import Dict, List

from replay_tpu.data.nn.schema import TensorSchema

from .transforms import (
    CopyTransform,
    EqualityMaskTransform,
    GroupTransform,
    InBatchNegativeSamplingTransform,
    NextTokenTransform,
    RenameTransform,
    SegmentBoundaryMaskTransform,
    TokenMaskTransform,
    Transform,
    UnsqueezeTransform,
)


def make_default_sasrec_transforms(tensor_schema: TensorSchema) -> Dict[str, List[Transform]]:
    """Next-token-prediction pipelines keyed by split (train/validate/test/predict)."""
    item_id = tensor_schema.item_id_feature_name
    sequential = [f.name for f in tensor_schema.all_features if f.is_seq]
    train = [
        NextTokenTransform(label_name=item_id, shift=1, apply_to=sequential),
        RenameTransform({f"{item_id}_mask": "padding_mask", "positive_labels_mask": "target_padding_mask"}),
        UnsqueezeTransform("target_padding_mask", -1),
        UnsqueezeTransform("positive_labels", -1),
        GroupTransform({"feature_tensors": list(tensor_schema.names)}),
    ]
    eval_pipeline = [
        RenameTransform({f"{item_id}_mask": "padding_mask"}),
        GroupTransform({"feature_tensors": list(tensor_schema.names)}),
    ]
    return {
        "train": train,
        "validate": list(eval_pipeline),
        "test": list(eval_pipeline),
        "predict": list(eval_pipeline),
    }


def make_packed_sasrec_transforms(tensor_schema: TensorSchema) -> Dict[str, List[Transform]]:
    """Next-token pipelines for PACKED batches (PackedSequenceBatcher output).

    Identical to the SASRec template plus the packing fixups: labels that
    would cross a packed segment boundary are masked out of
    ``target_padding_mask``, and ``segment_ids`` is trimmed to the input
    length and left TOP-LEVEL in the batch (outside ``feature_tensors``) so
    the trainer's signature filtering hands it to the model's attention path
    (docs/performance.md "Feeding the beast").
    """
    item_id = tensor_schema.item_id_feature_name
    sequential = [f.name for f in tensor_schema.all_features if f.is_seq]
    train = [
        NextTokenTransform(label_name=item_id, shift=1, apply_to=sequential),
        RenameTransform({f"{item_id}_mask": "padding_mask", "positive_labels_mask": "target_padding_mask"}),
        # order matters: runs on the FULL-length segment ids (NextToken left
        # them untrimmed), masks boundary labels, then input-aligns them
        SegmentBoundaryMaskTransform(segment_name="segment_ids", mask_name="target_padding_mask", shift=1),
        UnsqueezeTransform("target_padding_mask", -1),
        UnsqueezeTransform("positive_labels", -1),
        GroupTransform({"feature_tensors": list(tensor_schema.names)}),
    ]
    eval_pipeline = [
        RenameTransform({f"{item_id}_mask": "padding_mask"}),
        GroupTransform({"feature_tensors": list(tensor_schema.names)}),
    ]
    return {
        "train": train,
        "validate": list(eval_pipeline),
        "test": list(eval_pipeline),
        "predict": list(eval_pipeline),
    }


def make_default_twotower_transforms(tensor_schema: TensorSchema) -> Dict[str, List[Transform]]:
    """SASRec's next-token pipelines + in-batch negatives for retrieval training
    (ref nn/transform/template/twotower.py:8; the in-batch pool replaces global
    uniform sampling — SURVEY.md §6 TwoTower config)."""
    pipelines = make_default_sasrec_transforms(tensor_schema)
    pipelines["train"].append(InBatchNegativeSamplingTransform())
    return pipelines


def make_default_bert4rec_transforms(
    tensor_schema: TensorSchema, mask_prob: float = 0.15
) -> Dict[str, List[Transform]]:
    """Masked-LM pipelines: targets are the original items at masked positions
    (token_mask False = masked = predict here), matching the Bert4Rec training
    contract (ref bert4rec/dataset.py:95)."""
    item_id = tensor_schema.item_id_feature_name
    train = [
        RenameTransform({f"{item_id}_mask": "padding_mask"}),
        TokenMaskTransform(token_name="padding_mask", mask_prob=mask_prob),
        CopyTransform({item_id: "positive_labels", "padding_mask": "target_padding_mask"}),
        # target positions = real tokens that were masked out
        EqualityMaskTransform(
            feature_name="token_mask",
            mask_name="target_padding_mask",
            equality_value=False,
            op="and",
        ),
        UnsqueezeTransform("positive_labels", -1),
        UnsqueezeTransform("target_padding_mask", -1),
        GroupTransform({"feature_tensors": list(tensor_schema.names)}),
    ]
    eval_pipeline = [
        RenameTransform({f"{item_id}_mask": "padding_mask"}),
        GroupTransform({"feature_tensors": list(tensor_schema.names)}),
    ]
    return {
        "train": train,
        "validate": list(eval_pipeline),
        "test": list(eval_pipeline),
        "predict": list(eval_pipeline),
    }
