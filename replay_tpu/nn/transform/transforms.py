"""Composable batch transforms over dict batches.

Capability parity with replay/nn/transform/*.py (~830 LoC): NextToken, negative
sampling (uniform + multi-class), TokenMask, SequenceRoll, Trim/AdaptiveTrim,
EqualityMask, Copy, Rename, Select, Unsqueeze, Group, composed per split.

JAX design: every transform is a pure callable ``batch, rng -> batch`` on jnp/numpy
arrays (no module state); randomness comes from an explicit PRNG key threaded by
:class:`Compose`. All ops are static-shape except ``AdaptiveTrimTransform``, which is
host-side only (data-dependent length) and documented as such.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

DEFAULT_MASK_POSTFIX = "_mask"
Batch = Dict[str, jnp.ndarray]


class Transform:
    """Base: a pure batch→batch function; ``needs_rng`` marks stochastic transforms."""

    needs_rng = False

    def __call__(self, batch: Batch, rng: Optional[jax.Array] = None) -> Batch:
        raise NotImplementedError


class Compose(Transform):
    """Apply transforms in order, splitting the rng across the stochastic ones."""

    def __init__(self, transforms: Sequence[Transform]) -> None:
        self.transforms = list(transforms)

    @property
    def needs_rng(self) -> bool:  # type: ignore[override]
        return any(t.needs_rng for t in self.transforms)

    def __call__(self, batch: Batch, rng: Optional[jax.Array] = None) -> Batch:
        for transform in self.transforms:
            if transform.needs_rng:
                if rng is None:
                    msg = f"{type(transform).__name__} needs an rng key"
                    raise ValueError(msg)
                rng, sub = jax.random.split(rng)
                batch = transform(batch, sub)
            else:
                batch = transform(batch)
        return batch


class NextTokenTransform(Transform):
    """Shift ``label_name`` by ``shift`` to build ``positive_labels`` (+ its mask);
    trim the last ``shift`` steps off the declared sequential features.

    ``apply_to`` names the sequential features (and their masks) to trim — only
    those are touched, so non-sequence [B, N] tensors such as sampled
    ``negative_labels`` pass through untouched (the reference trims only schema
    sequential features). When ``apply_to`` is None every ndim>=2 tensor not in
    ``ignore`` is trimmed, which is only safe if the batch holds nothing but
    sequences.
    """

    def __init__(
        self,
        label_name: str,
        shift: int = 1,
        ignore: Union[List[str], str, None] = None,
        apply_to: Union[List[str], str, None] = None,
        out_feature_name: str = "positive_labels",
        mask_postfix: str = DEFAULT_MASK_POSTFIX,
    ) -> None:
        self.label_name = label_name
        self.shift = shift
        self.ignore = [ignore] if isinstance(ignore, str) else list(ignore or [])
        if apply_to is not None:
            apply_to = [apply_to] if isinstance(apply_to, str) else list(apply_to)
            # trim a feature's mask together with the feature
            apply_to = list(
                dict.fromkeys(apply_to + [f"{name}{mask_postfix}" for name in apply_to])
            )
        self.apply_to = apply_to
        self.out_feature_name = out_feature_name
        self.mask_postfix = mask_postfix

    def _should_trim(self, name: str, value) -> bool:
        if name in self.ignore or value.ndim < 2:
            return False
        if self.apply_to is not None:
            return name in self.apply_to
        return True

    def __call__(self, batch: Batch, rng=None) -> Batch:
        shift = self.shift
        labels = batch[self.label_name][:, shift:]
        label_mask_name = f"{self.label_name}{self.mask_postfix}"
        out = {}
        for name, value in batch.items():
            out[name] = value[:, :-shift] if self._should_trim(name, value) else value
        out[self.out_feature_name] = labels
        if label_mask_name in batch:
            out[f"{self.out_feature_name}{self.mask_postfix}"] = batch[label_mask_name][:, shift:]
        else:
            out[f"{self.out_feature_name}{self.mask_postfix}"] = jnp.ones_like(labels, dtype=bool)
        return out


class UniformNegativeSamplingTransform(Transform):
    """Sample ``num_negative_samples`` global negatives per batch (without replacement)."""

    needs_rng = True

    def __init__(
        self,
        cardinality: int,
        num_negative_samples: int,
        *,
        out_feature_name: str = "negative_labels",
        sample_distribution: Optional[jnp.ndarray] = None,
    ) -> None:
        if num_negative_samples >= cardinality:
            msg = (
                f"num_negative_samples ({num_negative_samples}) must be < cardinality "
                f"({cardinality})"
            )
            raise ValueError(msg)
        if sample_distribution is not None and sample_distribution.shape[-1] != cardinality:
            msg = "sample_distribution size must match cardinality"
            raise ValueError(msg)
        self.cardinality = cardinality
        self.num_negative_samples = num_negative_samples
        self.out_feature_name = out_feature_name
        self.sample_distribution = sample_distribution

    def __call__(self, batch: Batch, rng=None) -> Batch:
        if self.sample_distribution is None:
            negatives = jax.random.choice(
                rng, self.cardinality, shape=(self.num_negative_samples,), replace=False
            )
        else:
            probs = self.sample_distribution / jnp.sum(self.sample_distribution)
            negatives = jax.random.choice(
                rng, self.cardinality, shape=(self.num_negative_samples,), replace=False, p=probs
            )
        return {**batch, self.out_feature_name: negatives}


class MultiClassNegativeSamplingTransform(Transform):
    """Per-row negatives sampled from class-conditional distributions.

    ``class_assignment`` maps each item to a class; for each batch row the sampler
    draws negatives from the items of the same class as the row's reference item
    (reference: replay/nn/transform/negative_sampling.py:82).
    """

    needs_rng = True

    def __init__(
        self,
        class_assignment: jnp.ndarray,  # [num_items] int class per item
        num_negative_samples: int,
        reference_name: str = "item_id",
        out_feature_name: str = "negative_labels",
    ) -> None:
        import numpy as np

        class_assignment = np.asarray(class_assignment)
        self.class_assignment = jnp.asarray(class_assignment)
        self.num_negative_samples = num_negative_samples
        self.reference_name = reference_name
        self.out_feature_name = out_feature_name
        # per-class item-id index lists padded to the largest class: sampling draws a
        # random index into the class's list instead of materializing a [B, num_items]
        # probability matrix (which would blow up memory on large catalogs)
        num_classes = int(class_assignment.max()) + 1
        members = [np.flatnonzero(class_assignment == c) for c in range(num_classes)]
        empty = [c for c, m in enumerate(members) if len(m) == 0]
        if empty:
            msg = (
                f"class_assignment has empty classes {empty}: every draw for such a "
                "class would silently return item 0. Use contiguous class ids."
            )
            raise ValueError(msg)
        sizes = np.array([len(m) for m in members], dtype=np.int32)
        table = np.zeros((num_classes, int(sizes.max())), dtype=np.int32)
        for c, m in enumerate(members):
            if len(m):
                table[c, : len(m)] = m
        self._class_items = jnp.asarray(table)  # [num_classes, max_class_size]
        self._class_sizes = jnp.asarray(sizes)  # [num_classes]

    def __call__(self, batch: Batch, rng=None) -> Batch:
        reference = batch[self.reference_name]
        last_items = reference[:, -1] if reference.ndim > 1 else reference
        classes = self.class_assignment[jnp.clip(last_items, 0, self.class_assignment.shape[0] - 1)]
        draws = jax.random.randint(
            rng, (classes.shape[0], self.num_negative_samples), 0, jnp.iinfo(jnp.int32).max
        )
        indices = draws % self._class_sizes[classes][:, None]
        negatives = jnp.take_along_axis(self._class_items[classes], indices, axis=1)
        return {**batch, self.out_feature_name: negatives}


class InBatchNegativeSamplingTransform(Transform):
    """Use the batch's own positives as the shared negative pool (two-tower
    retrieval training: every query scores against every other query's target).

    Emits ``out_feature_name`` of shape [B] — the `[N]` shared-pool form the
    sampled losses broadcast; own-positive collisions stay in the denominator,
    the standard in-batch-softmax formulation.
    """

    def __init__(
        self,
        label_name: str = "positive_labels",
        out_feature_name: str = "negative_labels",
    ) -> None:
        self.label_name = label_name
        self.out_feature_name = out_feature_name

    def __call__(self, batch: Batch, rng=None) -> Batch:
        labels = batch[self.label_name]
        while labels.ndim > 1:  # [B, L, P] -> last position's positive per row
            labels = labels[:, -1]
        return {**batch, self.out_feature_name: labels}


class SegmentBoundaryMaskTransform(Transform):
    """Packed-batch fixup after :class:`NextTokenTransform`: mask labels that
    cross a segment boundary, and trim ``segment_ids`` to the input length.

    A packed row concatenates several user sequences (segment ids 1..k, 0 on
    padding). The next-token shift assigns position ``t`` the label at
    original position ``t + shift`` — at the last positions of a segment that
    label belongs to the NEXT user's sequence. This transform ANDs the target
    mask with "label position is in the SAME (non-padding) segment as the
    input position", so the loss never trains across a packed boundary, then
    replaces the full-length ``segment_ids`` with its input-aligned
    ``[:, :-shift]`` view (what the model's attention mask consumes).
    Run it after the rename that produced ``mask_name`` and before the
    unsqueeze/group steps.
    """

    def __init__(
        self,
        segment_name: str = "segment_ids",
        mask_name: str = "target_padding_mask",
        shift: int = 1,
    ) -> None:
        if shift < 1:
            msg = "shift must be >= 1 (the NextTokenTransform shift)"
            raise ValueError(msg)
        self.segment_name = segment_name
        self.mask_name = mask_name
        self.shift = shift

    def __call__(self, batch: Batch, rng=None) -> Batch:
        segments = batch[self.segment_name]
        shift = self.shift
        if segments.shape[-1] == batch[self.mask_name].shape[1]:
            msg = (
                f"'{self.segment_name}' is already trimmed to the label "
                f"length; run {type(self).__name__} on the FULL-length "
                "segment ids (before any trim), after NextTokenTransform "
                f"excluded '{self.segment_name}' from apply_to."
            )
            raise ValueError(msg)
        inputs = segments[:, :-shift]
        labels_seg = segments[:, shift:]
        same_segment = (inputs == labels_seg) & (labels_seg != 0) & (inputs != 0)
        return {
            **batch,
            self.mask_name: batch[self.mask_name] & same_segment,
            self.segment_name: inputs,
        }


class TokenMaskTransform(Transform):
    """BERT-style keep-mask: True = visible token, False = masked-out token.

    Corner-case handling mirrors the reference (replay/nn/transform/token_mask.py:44):
    a row with nothing masked gets its LAST valid token masked; a row with everything
    masked gets its second-to-last position kept.
    """

    needs_rng = True

    def __init__(
        self,
        token_name: str,
        out_feature_name: str = "token_mask",
        mask_prob: float = 0.15,
        mask_postfix: str = DEFAULT_MASK_POSTFIX,
    ) -> None:
        self.token_name = token_name
        self.out_feature_name = out_feature_name
        self.mask_prob = mask_prob
        self.mask_postfix = mask_postfix

    def __call__(self, batch: Batch, rng=None) -> Batch:
        padding = batch[self.token_name]
        if padding.dtype != jnp.bool_:
            msg = "Source tensor for token mask must be boolean (a padding mask)."
            raise ValueError(msg)
        uniform = jax.random.uniform(rng, padding.shape)
        keep = (uniform * padding) >= self.mask_prob  # padded positions always False

        valid_count = padding.sum(axis=1)
        kept_count = (keep & padding).sum(axis=1)
        # nothing masked -> mask the last valid position
        all_kept = kept_count == valid_count
        last_valid = padding.shape[1] - 1 - jnp.argmax(padding[:, ::-1], axis=1)
        rows = jnp.arange(padding.shape[0])
        keep = keep.at[rows, last_valid].set(
            jnp.where(all_kept, False, keep[rows, last_valid])
        )
        # everything masked -> keep the position before the last valid one
        none_kept = (kept_count == 0) & (valid_count > 1)
        before_last = jnp.maximum(last_valid - 1, 0)
        keep = keep.at[rows, before_last].set(
            jnp.where(none_kept, True, keep[rows, before_last])
        )
        return {**batch, self.out_feature_name: keep}


class SequenceRollTransform(Transform):
    """Roll a sequence along the time axis, refilling the vacated slots with padding."""

    def __init__(self, feature_name: str, roll: int = 1, padding_value: int = 0) -> None:
        if roll == 0:
            msg = "roll must be non-zero"
            raise ValueError(msg)
        self.feature_name = feature_name
        self.roll = roll
        self.padding_value = padding_value

    def __call__(self, batch: Batch, rng=None) -> Batch:
        rolled = jnp.roll(batch[self.feature_name], self.roll, axis=1)
        if self.roll > 0:
            rolled = rolled.at[:, : self.roll].set(self.padding_value)
        else:
            rolled = rolled.at[:, self.roll :].set(self.padding_value)
        return {**batch, self.feature_name: rolled}


class TrimTransform(Transform):
    """Keep the LAST ``seq_len`` positions of the named (left-padded) sequences."""

    def __init__(self, seq_len: int, feature_names: Union[List[str], str]) -> None:
        self.seq_len = seq_len
        self.feature_names = [feature_names] if isinstance(feature_names, str) else list(feature_names)

    def __call__(self, batch: Batch, rng=None) -> Batch:
        out = dict(batch)
        for name in self.feature_names:
            if batch[name].shape[1] < self.seq_len:
                msg = f"Cannot trim '{name}' of length {batch[name].shape[1]} to {self.seq_len}"
                raise ValueError(msg)
            out[name] = batch[name][:, -self.seq_len :]
        return out


class AdaptiveTrimTransform(Transform):
    """Trim to the batch's longest real sequence. HOST-ONLY: data-dependent shape,
    do not use inside jit (reference: replay/nn/transform/trim.py:50)."""

    def __init__(self, feature_names: Union[List[str], str], padding_mask_name: str = "padding_mask") -> None:
        self.feature_names = [feature_names] if isinstance(feature_names, str) else list(feature_names)
        self.padding_mask_name = padding_mask_name

    def __call__(self, batch: Batch, rng=None) -> Batch:
        if self.padding_mask_name not in batch:
            msg = f"Padding mask '{self.padding_mask_name}' not found in batch."
            raise KeyError(msg)
        mask = batch[self.padding_mask_name]
        max_len = int(mask.sum(axis=1).max())
        if max_len == mask.shape[1]:
            return batch
        out = dict(batch)
        for name in self.feature_names:
            out[name] = batch[name][:, -max_len:]
        return out


class EqualityMaskTransform(Transform):
    """Combine ``mask_name`` with (feature == value) under AND/OR/XOR."""

    _OPS = {
        "and": jnp.logical_and,
        "or": jnp.logical_or,
        "xor": jnp.logical_xor,
    }

    def __init__(self, feature_name: str, mask_name: str, equality_value, op: str = "and") -> None:
        if op not in self._OPS:
            msg = f"op must be one of {sorted(self._OPS)}"
            raise ValueError(msg)
        self.feature_name = feature_name
        self.mask_name = mask_name
        self.equality_value = equality_value
        self.op = op

    def __call__(self, batch: Batch, rng=None) -> Batch:
        modification = batch[self.feature_name] == self.equality_value
        combined = self._OPS[self.op](batch[self.mask_name], modification)
        return {**batch, self.mask_name: combined}


class CopyTransform(Transform):
    def __init__(self, mapping: Dict[str, str]) -> None:
        self.mapping = mapping

    def __call__(self, batch: Batch, rng=None) -> Batch:
        out = dict(batch)
        for src, dst in self.mapping.items():
            out[dst] = batch[src]
        return out


class RenameTransform(Transform):
    def __init__(self, mapping: Dict[str, str]) -> None:
        self.mapping = mapping

    def __call__(self, batch: Batch, rng=None) -> Batch:
        out = {}
        for name, value in batch.items():
            out[self.mapping.get(name, name)] = value
        return out


class SelectTransform(Transform):
    def __init__(self, feature_names: List[str]) -> None:
        self.feature_names = list(feature_names)

    def __call__(self, batch: Batch, rng=None) -> Batch:
        return {name: batch[name] for name in self.feature_names}


class UnsqueezeTransform(Transform):
    def __init__(self, feature_name: str, axis: int = -1) -> None:
        self.feature_name = feature_name
        self.axis = axis

    def __call__(self, batch: Batch, rng=None) -> Batch:
        return {**batch, self.feature_name: jnp.expand_dims(batch[self.feature_name], self.axis)}


class GroupTransform(Transform):
    """Nest the named features under a sub-dict key (e.g. ``feature_tensors``)."""

    def __init__(self, mapping: Dict[str, List[str]]) -> None:
        self.mapping = mapping

    def __call__(self, batch: Batch, rng=None) -> Batch:
        grouped_names = {name for names in self.mapping.values() for name in names}
        out = {name: value for name, value in batch.items() if name not in grouped_names}
        for group, names in self.mapping.items():
            out[group] = {name: batch[name] for name in names if name in batch}
        return out
