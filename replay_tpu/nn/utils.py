"""Small nn helpers.

Capability parity with the reference ``replay/nn/utils.py:18-29``
(``create_activation``): resolve an activation by name. JAX activations are
plain functions rather than modules, so this returns a callable.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax.numpy as jnp

_ACTIVATIONS = {
    "relu": nn.relu,
    "gelu": nn.gelu,
    "sigmoid": nn.sigmoid,
    "silu": nn.silu,
}


def create_activation(name: str) -> Callable[[jnp.ndarray], jnp.ndarray]:
    """Activation function by name (``relu`` / ``gelu`` / ``sigmoid`` / ``silu``)."""
    if name not in _ACTIVATIONS:
        msg = f"Expected activation one of {sorted(_ACTIVATIONS)}, got {name!r}"
        raise ValueError(msg)
    return _ACTIVATIONS[name]
