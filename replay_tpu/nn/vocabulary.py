"""Item-vocabulary surgery on trained parameters.

Capability parity with the reference's continual-catalog operations
(replay/models/nn/sequential/sasrec/lightning.py:493-568:
``set_item_embeddings_by_size`` / ``set_item_embeddings_by_tensor`` /
``append_item_embeddings``): grow or replace the item-embedding table of an
ALREADY-TRAINED model when the catalog changes between retrains.

Pure functional: params in, params out. The padding row stays the LAST table
row (the weight-tying alignment invariant, replay_tpu/nn/embedding.py), so
growth moves the padding row to the new end and initializes fresh rows from
the mean of the existing embeddings (the reference's default) or a caller
tensor. The schema object is updated in place (cardinality/padding move
together).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from replay_tpu.data.nn.schema import TensorSchema


def _find_table_path(params, feature_name: str):
    """Locate the '<...>/embedding_<feature>/table/embedding' leaf path."""
    # exact path segment: 'embedding_item' must NOT match 'embedding_item_category'
    marker = f"['embedding_{feature_name}']"
    matches = []

    def visit(path, leaf):
        path_str = jax.tree_util.keystr(path)
        if marker in path_str and path_str.endswith("['embedding']"):
            matches.append((path, leaf))
        return leaf

    jax.tree_util.tree_map_with_path(visit, params)
    if not matches:
        msg = f"No embedding table found for feature '{feature_name}'."
        raise ValueError(msg)
    return matches


def _replace_leaf(params, target_path, new_leaf):
    def swap(path, leaf):
        return new_leaf if path == target_path else leaf

    return jax.tree_util.tree_map_with_path(swap, params)


def resize_item_embeddings(
    params,
    schema: TensorSchema,
    new_cardinality: int,
    init_tensor: Optional[np.ndarray] = None,
) -> dict:
    """Grow (or shrink) the item table to ``new_cardinality`` (+1 padding row).

    Existing item rows are preserved; new rows come from ``init_tensor`` when
    given (``[new_items, E]`` for the appended rows or ``[new_cardinality, E]``
    for a full replacement) else from the mean of the existing rows. The
    schema's ITEM_ID cardinality (and its default padding value) is updated.
    """
    feature_name = schema.item_id_feature_name
    if feature_name is None:
        msg = "Schema has no ITEM_ID feature."
        raise ValueError(msg)
    old_cardinality = schema[feature_name].cardinality
    resized = 0
    for path, table in _find_table_path(params, feature_name):
        table = np.asarray(table)
        rows, dim = table.shape
        if rows != old_cardinality + 1:
            msg = (
                f"Item table at {jax.tree_util.keystr(path)} has {rows} rows; the "
                f"schema says {old_cardinality}+1 — params and schema are out of "
                "sync (was resize applied twice to the same state?)."
            )
            raise ValueError(msg)
        resized += 1
        items, padding_row = table[:old_cardinality], table[old_cardinality:]
        if init_tensor is not None and len(init_tensor) == new_cardinality:
            new_items = np.asarray(init_tensor, table.dtype)
        elif new_cardinality <= old_cardinality:
            if init_tensor is not None:
                msg = (
                    f"init_tensor has {len(init_tensor)} rows; a shrink to "
                    f"{new_cardinality} items accepts only a full [new_cardinality, E] table."
                )
                raise ValueError(msg)
            new_items = items[:new_cardinality]
        else:
            extra = (
                np.asarray(init_tensor, table.dtype)
                if init_tensor is not None
                else np.tile(items.mean(axis=0, keepdims=True), (new_cardinality - old_cardinality, 1))
            )
            if len(extra) != new_cardinality - old_cardinality:
                msg = (
                    f"init_tensor has {len(extra)} rows; expected "
                    f"{new_cardinality - old_cardinality} appended or {new_cardinality} total."
                )
                raise ValueError(msg)
            new_items = np.concatenate([items, extra])
        new_table = np.concatenate([new_items, padding_row])  # padding stays LAST
        params = _replace_leaf(params, path, new_table.astype(table.dtype))
    schema[feature_name]._set_cardinality(new_cardinality)
    # let the padding default re-resolve to the new cardinality (last-row invariant)
    schema[feature_name]._padding_value = None
    return params


def append_item_embeddings(params, schema: TensorSchema, new_rows: np.ndarray) -> dict:
    """Append ``[K, E]`` rows for K new catalog items (ref append_item_embeddings)."""
    feature_name = schema.item_id_feature_name
    new_rows = np.atleast_2d(np.asarray(new_rows))
    return resize_item_embeddings(
        params, schema, schema[feature_name].cardinality + len(new_rows), new_rows
    )


def set_item_embeddings(params, schema: TensorSchema, table: np.ndarray) -> dict:
    """Replace the whole item table with ``[num_items, E]`` (ref
    set_item_embeddings_by_tensor)."""
    return resize_item_embeddings(params, schema, len(table), np.asarray(table))


# reference-exact name (replay/models/nn/sequential/bert4rec/lightning.py:528)
set_item_embeddings_by_tensor = set_item_embeddings


def set_item_embeddings_by_size(
    params,
    schema: TensorSchema,
    new_cardinality: int,
    rng: Optional[jax.Array] = None,
) -> dict:
    """Grow to ``new_cardinality`` with xavier-normal rows for the NEW items —
    the reference's expansion recipe (lightning.py:507-523: keep fitted rows,
    ``xavier_normal_`` the rest). ``resize_item_embeddings`` with no tensor
    gives mean-init instead; this wrapper matches the reference init.

    The reference xaviers the FULL ``(new_cardinality + 1, dim)`` table and
    copies the fitted rows back over it, so the new rows' std derives from the
    whole table's fan — reproduced here by drawing the slice at that std."""
    feature_name = schema.item_id_feature_name
    if feature_name is None:
        msg = "Schema has no ITEM_ID feature."
        raise ValueError(msg)
    old_cardinality = schema[feature_name].cardinality
    if new_cardinality <= old_cardinality:
        msg = "New vocabulary size must be greater than already fitted"
        raise ValueError(msg)
    dim = np.asarray(
        _find_table_path(params, feature_name)[0][1]
    ).shape[1]
    std = float(np.sqrt(2.0 / ((new_cardinality + 1) + dim)))
    key = rng if rng is not None else jax.random.PRNGKey(0)
    fresh = np.asarray(
        jax.random.normal(key, (new_cardinality - old_cardinality, dim), np.float32)
    ) * std
    return resize_item_embeddings(params, schema, new_cardinality, fresh)


def get_item_embeddings(params, schema: TensorSchema) -> np.ndarray:
    """The fitted item rows ``[cardinality, E]``, padding row excluded (the
    reference's ``get_all_embeddings`` for the item table, lightning.py:501)."""
    feature_name = schema.item_id_feature_name
    if feature_name is None:
        msg = "Schema has no ITEM_ID feature."
        raise ValueError(msg)
    table = np.asarray(_find_table_path(params, feature_name)[0][1])
    return table[: schema[feature_name].cardinality]
