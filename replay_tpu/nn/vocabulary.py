"""Item-vocabulary surgery on trained parameters (and optimizer state).

Capability parity with the reference's continual-catalog operations
(replay/models/nn/sequential/sasrec/lightning.py:493-568:
``set_item_embeddings_by_size`` / ``set_item_embeddings_by_tensor`` /
``append_item_embeddings``): grow or replace the item-embedding table of an
ALREADY-TRAINED model when the catalog changes between retrains.

Pure functional: params in, params out. The padding row stays the LAST table
row (the weight-tying alignment invariant, replay_tpu/nn/embedding.py), so
growth moves the padding row to the new end and initializes fresh rows from
the mean of the existing embeddings (the reference's default) or a caller
tensor. The schema object is updated in place (cardinality/padding move
together).

Mid-RUN growth (the continual-training loop, docs/robustness.md) additionally
needs the OPTIMIZER state resized in lockstep: Adam's ``mu``/``nu`` mirror the
params tree, so a grown table with stale moment rows either crashes deep in
optax or — worse — silently reinitializes the moments and loses the trained
second-moment scale. :func:`resize_optimizer_state` applies the same row
surgery to every moment leaf at the table's path (existing rows keep their
moments, cold rows start at zero — a fresh Adam row, exactly what a
newly-initialized embedding row would get — and the padding row's moments move
to the new end with it), and :func:`validate_optimizer_state` rejects a
params/opt-state pair whose table shapes drifted apart, naming the offending
path.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from replay_tpu.data.nn.schema import TensorSchema


def _find_table_path(params, feature_name: str):
    """Locate the '<...>/embedding_<feature>/table/embedding' leaf path."""
    # exact path segment: 'embedding_item' must NOT match 'embedding_item_category'
    marker = f"['embedding_{feature_name}']"
    matches = []

    def visit(path, leaf):
        path_str = jax.tree_util.keystr(path)
        if marker in path_str and path_str.endswith("['embedding']"):
            matches.append((path, leaf))
        return leaf

    jax.tree_util.tree_map_with_path(visit, params)
    if not matches:
        msg = f"No embedding table found for feature '{feature_name}'."
        raise ValueError(msg)
    return matches


def _replace_leaf(params, target_path, new_leaf):
    def swap(path, leaf):
        return new_leaf if path == target_path else leaf

    return jax.tree_util.tree_map_with_path(swap, params)


def _find_moment_leaves(opt_state, feature_name: str):
    """Locate every optimizer-state leaf that mirrors the item table (Adam
    ``mu``/``nu`` rows and friends): same path marker, same trailing key."""
    marker = f"['embedding_{feature_name}']"
    matches = []

    def visit(path, leaf):
        path_str = jax.tree_util.keystr(path)
        if marker in path_str and path_str.endswith("['embedding']"):
            matches.append((path, leaf))
        return leaf

    jax.tree_util.tree_map_with_path(visit, opt_state)
    return matches


def resize_optimizer_state(
    opt_state,
    feature_name: str,
    old_cardinality: int,
    new_cardinality: int,
):
    """Resize every item-table moment leaf in ``opt_state`` to match a table
    grown/shrunk to ``new_cardinality`` (+1 padding row).

    Existing rows keep their trained moments, cold rows get ZEROS (a fresh
    Adam row — bias correction then treats them exactly like a
    newly-initialized parameter), and the padding row's moments move to the
    new last position alongside the padding row itself. Leaves whose row
    count does not match ``old_cardinality + 1`` raise, naming the path —
    the params/opt-state-out-of-sync guard.

    Returns ``(opt_state, resized_leaf_count)``; a momentum-free optimizer
    (plain SGD) has no table-shaped state and returns the input unchanged
    with count 0.
    """
    resized = 0
    for path, leaf in _find_moment_leaves(opt_state, feature_name):
        table = np.asarray(leaf)
        rows, _ = table.shape
        if rows != old_cardinality + 1:
            msg = (
                f"Optimizer-state leaf at {jax.tree_util.keystr(path)} has "
                f"{rows} rows; the params table says {old_cardinality}+1 — "
                "params and optimizer state are out of sync (was the table "
                "resized without its moments?)."
            )
            raise ValueError(msg)
        items, padding_row = table[:old_cardinality], table[old_cardinality:]
        if new_cardinality <= old_cardinality:
            new_items = items[:new_cardinality]
        else:
            cold = np.zeros((new_cardinality - old_cardinality, table.shape[1]), table.dtype)
            new_items = np.concatenate([items, cold])
        new_leaf = np.concatenate([new_items, padding_row]).astype(table.dtype)
        opt_state = _replace_leaf(opt_state, path, new_leaf)
        resized += 1
    return opt_state, resized


def validate_optimizer_state(params, opt_state, schema: TensorSchema) -> None:
    """Reject a ``(params, opt_state)`` pair whose item-table shapes drifted
    apart — the guard a resumed/continued fit runs BEFORE training, so a
    mid-run catalog grow with stale optimizer state fails loudly (naming the
    table path) instead of crashing deep in optax or silently reinitializing
    the moments. Schemas without an ITEM_ID feature validate trivially."""
    feature_name = schema.item_id_feature_name
    if feature_name is None:
        return
    try:
        table_shape = np.shape(_find_table_path(params, feature_name)[0][1])
    except ValueError:
        return  # no item table in this model's params (nothing to check)
    for path, leaf in _find_moment_leaves(opt_state, feature_name):
        if tuple(np.shape(leaf)) != tuple(table_shape):
            msg = (
                f"Optimizer-state leaf at {jax.tree_util.keystr(path)} has shape "
                f"{tuple(np.shape(leaf))} but the item table "
                f"'embedding_{feature_name}' is {tuple(table_shape)} — the "
                "catalog was resized without its optimizer moments. Resize "
                "both together (Trainer.resize_vocabulary(carry_opt_state=True) "
                "or vocabulary.resize_optimizer_state) before fitting."
            )
            raise ValueError(msg)


def resize_item_embeddings(
    params,
    schema: TensorSchema,
    new_cardinality: int,
    init_tensor: Optional[np.ndarray] = None,
    opt_state=None,
):
    """Grow (or shrink) the item table to ``new_cardinality`` (+1 padding row).

    Existing item rows are preserved; new rows come from ``init_tensor`` when
    given (``[new_items, E]`` for the appended rows or ``[new_cardinality, E]``
    for a full replacement) else from the mean of the existing rows. The
    schema's ITEM_ID cardinality (and its default padding value) is updated.

    With ``opt_state`` supplied the matching optimizer moments are resized in
    LOCKSTEP (:func:`resize_optimizer_state`: trained rows keep their moments,
    cold rows start at zero) and ``(params, opt_state)`` is returned — the
    mid-run growth path; without it, just the resized ``params`` (the
    between-retrains path, where fresh optimizer state is built anyway).
    """
    feature_name = schema.item_id_feature_name
    if feature_name is None:
        msg = "Schema has no ITEM_ID feature."
        raise ValueError(msg)
    old_cardinality = schema[feature_name].cardinality
    resized = 0
    for path, table in _find_table_path(params, feature_name):
        table = np.asarray(table)
        rows, dim = table.shape
        if rows != old_cardinality + 1:
            msg = (
                f"Item table at {jax.tree_util.keystr(path)} has {rows} rows; the "
                f"schema says {old_cardinality}+1 — params and schema are out of "
                "sync (was resize applied twice to the same state?)."
            )
            raise ValueError(msg)
        resized += 1
        items, padding_row = table[:old_cardinality], table[old_cardinality:]
        if init_tensor is not None and len(init_tensor) == new_cardinality:
            new_items = np.asarray(init_tensor, table.dtype)
        elif new_cardinality <= old_cardinality:
            if init_tensor is not None:
                msg = (
                    f"init_tensor has {len(init_tensor)} rows; a shrink to "
                    f"{new_cardinality} items accepts only a full [new_cardinality, E] table."
                )
                raise ValueError(msg)
            new_items = items[:new_cardinality]
        else:
            extra = (
                np.asarray(init_tensor, table.dtype)
                if init_tensor is not None
                else np.tile(items.mean(axis=0, keepdims=True), (new_cardinality - old_cardinality, 1))
            )
            if len(extra) != new_cardinality - old_cardinality:
                msg = (
                    f"init_tensor has {len(extra)} rows; expected "
                    f"{new_cardinality - old_cardinality} appended or {new_cardinality} total."
                )
                raise ValueError(msg)
            new_items = np.concatenate([items, extra])
        new_table = np.concatenate([new_items, padding_row])  # padding stays LAST
        params = _replace_leaf(params, path, new_table.astype(table.dtype))
    schema[feature_name]._set_cardinality(new_cardinality)
    # let the padding default re-resolve to the new cardinality (last-row invariant)
    schema[feature_name]._padding_value = None
    if opt_state is not None:
        opt_state, _ = resize_optimizer_state(
            opt_state, feature_name, old_cardinality, new_cardinality
        )
        return params, opt_state
    return params


def append_item_embeddings(params, schema: TensorSchema, new_rows: np.ndarray) -> dict:
    """Append ``[K, E]`` rows for K new catalog items (ref append_item_embeddings)."""
    feature_name = schema.item_id_feature_name
    new_rows = np.atleast_2d(np.asarray(new_rows))
    return resize_item_embeddings(
        params, schema, schema[feature_name].cardinality + len(new_rows), new_rows
    )


def set_item_embeddings(params, schema: TensorSchema, table: np.ndarray) -> dict:
    """Replace the whole item table with ``[num_items, E]`` (ref
    set_item_embeddings_by_tensor)."""
    return resize_item_embeddings(params, schema, len(table), np.asarray(table))


# reference-exact name (replay/models/nn/sequential/bert4rec/lightning.py:528)
set_item_embeddings_by_tensor = set_item_embeddings


def set_item_embeddings_by_size(
    params,
    schema: TensorSchema,
    new_cardinality: int,
    rng: Optional[jax.Array] = None,
    opt_state=None,
):
    """Grow to ``new_cardinality`` with xavier-normal rows for the NEW items —
    the reference's expansion recipe (lightning.py:507-523: keep fitted rows,
    ``xavier_normal_`` the rest). ``resize_item_embeddings`` with no tensor
    gives mean-init instead; this wrapper matches the reference init.

    The reference xaviers the FULL ``(new_cardinality + 1, dim)`` table and
    copies the fitted rows back over it, so the new rows' std derives from the
    whole table's fan — reproduced here by drawing the slice at that std."""
    feature_name = schema.item_id_feature_name
    if feature_name is None:
        msg = "Schema has no ITEM_ID feature."
        raise ValueError(msg)
    old_cardinality = schema[feature_name].cardinality
    if new_cardinality <= old_cardinality:
        msg = "New vocabulary size must be greater than already fitted"
        raise ValueError(msg)
    dim = np.asarray(
        _find_table_path(params, feature_name)[0][1]
    ).shape[1]
    std = float(np.sqrt(2.0 / ((new_cardinality + 1) + dim)))
    key = rng if rng is not None else jax.random.PRNGKey(0)
    fresh = np.asarray(
        jax.random.normal(key, (new_cardinality - old_cardinality, dim), np.float32)
    ) * std
    return resize_item_embeddings(
        params, schema, new_cardinality, fresh, opt_state=opt_state
    )


def get_item_embeddings(params, schema: TensorSchema) -> np.ndarray:
    """The fitted item rows ``[cardinality, E]``, padding row excluded (the
    reference's ``get_all_embeddings`` for the item table, lightning.py:501)."""
    feature_name = schema.item_id_feature_name
    if feature_name is None:
        msg = "Schema has no ITEM_ID feature."
        raise ValueError(msg)
    table = np.asarray(_find_table_path(params, feature_name)[0][1])
    return table[: schema[feature_name].cardinality]
