"""Run-telemetry subsystem: trainer events, step timing, MFU, memory, compiles.

The reference stack gets training observability for free from PyTorch Lightning
(loggers, progress bars, callbacks — replay/nn/lightning/module.py:14-120 wires
them); this JAX stack has no Lightning, so the trainer emits structured
:class:`TrainerEvent` records to pluggable :class:`RunLogger` sinks instead,
and a collectors layer measures what Lightning never could: jit retraces
(:class:`CompileTracker`), device memory (:class:`MemoryMonitor`), steady-state
throughput (:class:`StepTelemetry`) and achieved-vs-peak FLOPs (:mod:`.mfu`).
:mod:`.trace` adds host-side span tracing + goodput accounting (where does
wall-clock go BETWEEN steps — ``trace.json`` + per-epoch phase fractions),
:mod:`.health` computes in-graph model-health diagnostics (per-group norms and
update ratios, activation stats, attention entropy, the ``HealthWatcher``
early warning), :mod:`.profile` parses ``jax.profiler`` captures into
per-``named_scope`` DEVICE-time attribution, :mod:`.roofline` classifies every
compiled program memory- vs compute-bound against the chip's peak FLOPs/
bandwidth tables (with HBM footprint + collective-bytes introspection via
:mod:`replay_tpu.parallel.introspect`), and :mod:`.report` is the run-report
CLI over the artifacts (``python -m replay_tpu.obs.report <run_dir>``).
The LIVE half (docs/observability.md): :mod:`.metrics` keeps a thread-safe
registry (counters/gauges/histograms) bridged from the same event stream,
:mod:`.exporter` serves it as a scrapeable Prometheus ``/metrics`` endpoint
(+ ``/snapshot`` JSON), and :mod:`.slo` evaluates declarative threshold rules
at step/batch cadence, emitting ``on_slo_violation`` through the same sinks.
The POST-MORTEM half: :mod:`.blackbox` is the SIGKILL-proof flight recorder
(an mmap ring every sink family bridges into; ``read_flight`` tolerates the
torn final record), ``obs.report --postmortem`` reconstructs a dead fleet's
last-known-activity timelines from rings + event shards + checkpoint
sidecars, and :mod:`.federate` merges N per-process ``/snapshot`` exporters
into ONE fleet-level ``/metrics``.
Beyond-parity — SURVEY.md §5.
"""

from .blackbox import BlackboxLogger, FlightLog, FlightRecorder, read_flight
from .collectors import CompileTracker, MemoryMonitor, StepTelemetry
from .federate import FleetFederator, federate_snapshots, scrape_snapshot
from .health import HealthConfig, HealthWatcher, flatten_health, health_metrics
from .events import (
    ConsoleLogger,
    JsonlLogger,
    MultiLogger,
    RunLogger,
    TensorBoardLogger,
    TrainerEvent,
)
from .exporter import MetricsExporter
from .metrics import MetricsLogger, MetricsRegistry
from .quality import (
    QUALITY_SLOS,
    DriftDetector,
    PopularityDescriptor,
    QualityMonitor,
    canary_quality_rules,
    population_stability_index,
    prequential_scores,
)
from .slo import SLORule, SLOWatchdog
from .mfu import (
    PEAK_BF16_TFLOPS,
    cost_analysis,
    flops_per_step,
    mfu,
    peak_tflops,
    program_costs,
)
from .profile import NAMED_SCOPES, attribute_capture, latest_capture, scope_of
from .roofline import (
    PEAK_HBM_GBPS,
    analyze_program,
    classify,
    of_ceiling,
    peak_bandwidth,
)
from .trace import (
    GOODPUT_SPANS,
    REQUEST_HOP_SPANS,
    SERVE_GOODPUT_SPANS,
    TraceContext,
    Tracer,
    goodput_breakdown,
    lifecycle_span,
    merge_traces,
    tail_attribution,
    traced_iterator,
)

__all__ = [
    "BlackboxLogger",
    "CompileTracker",
    "ConsoleLogger",
    "DriftDetector",
    "FleetFederator",
    "FlightLog",
    "FlightRecorder",
    "GOODPUT_SPANS",
    "HealthConfig",
    "HealthWatcher",
    "JsonlLogger",
    "MemoryMonitor",
    "MetricsExporter",
    "MetricsLogger",
    "MetricsRegistry",
    "MultiLogger",
    "NAMED_SCOPES",
    "REQUEST_HOP_SPANS",
    "SLORule",
    "SLOWatchdog",
    "PEAK_BF16_TFLOPS",
    "PEAK_HBM_GBPS",
    "PopularityDescriptor",
    "QUALITY_SLOS",
    "QualityMonitor",
    "RunLogger",
    "SERVE_GOODPUT_SPANS",
    "StepTelemetry",
    "TensorBoardLogger",
    "TraceContext",
    "Tracer",
    "TrainerEvent",
    "analyze_program",
    "attribute_capture",
    "canary_quality_rules",
    "classify",
    "cost_analysis",
    "federate_snapshots",
    "flatten_health",
    "flops_per_step",
    "goodput_breakdown",
    "health_metrics",
    "latest_capture",
    "lifecycle_span",
    "merge_traces",
    "mfu",
    "of_ceiling",
    "peak_bandwidth",
    "peak_tflops",
    "population_stability_index",
    "prequential_scores",
    "program_costs",
    "read_flight",
    "scope_of",
    "scrape_snapshot",
    "tail_attribution",
    "traced_iterator",
]
