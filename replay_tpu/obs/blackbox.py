"""The black box: an mmap-backed flight recorder that survives SIGKILL.

Every other sink in :mod:`replay_tpu.obs` is process-resident at exactly the
wrong moment: ``trace.json`` is written at fit end, the
:class:`~replay_tpu.obs.metrics.MetricsRegistry` evaporates unless a scraper
happened to hit ``/metrics`` first, and a supervisor's only forensic record of
a dead worker is an in-memory stderr tail. This module is the sink that is
still there after ``kill -9``:

* :class:`FlightRecorder` — a fixed-width-record ring buffer in an mmap'd
  file. A write is an O(1) in-place store into slot ``(seqno - 1) % capacity``
  (no append, no rotation, no allocation on the hot path); each record carries
  its own seqno and a CRC32 over the framed bytes. The process never calls
  ``msync`` per record: dirty pages live in the OS page cache, which outlives
  the process — SIGKILL, an OOM kill, a segfault all leave the last
  ``capacity`` records readable. Only machine death (power loss before
  writeback) loses the tail; :meth:`FlightRecorder.flush` exists for callers
  that want a durability point (close does one).

* :func:`read_flight` — the post-mortem reader. It never trusts a byte: a
  slot is ``empty`` only when ALL its bytes are zero (a preallocated slot the
  writer never reached); anything else must frame-parse AND pass CRC AND
  decode as JSON to be returned. The one record a SIGKILL can tear — the
  in-progress store — fails CRC and is surfaced as ``torn_tail=True`` on the
  returned :class:`FlightLog`, never as an exception and never as a corrupt
  record in ``records``.

* :class:`BlackboxLogger` — the bridge. It is a
  :class:`~replay_tpu.obs.events.RunLogger`, so attaching it to the existing
  event fan-out (``Trainer.fit(flight_path=...)``,
  ``ScoringService(flight_path=...)``, a ``loggers=`` list) IS the
  instrumentation — the PR-10 pattern: train steps, anomalies, health
  fetches, serve batches, shed/breaker/degrade, heartbeats and
  swap/promotion events all flow through ``log_event`` already; this sink
  just packs each one into a flight record. No new Trainer or ScoringService
  hooks exist for it.

Record framing (little-endian, ``RECORD_HEADER = "<QIHd"``)::

    [ seqno u64 | crc u32 | length u16 | time f64 | payload[length] | zeros ]

``crc = crc32(pack("<QHd", seqno, length, time) + payload)`` — the seqno is
inside the checksum so a stale slot from a previous lap can never be
mis-attributed to the current one. Payloads are compact JSON; the encoder
whittles oversized events (drop the bulkiest values first, always keep the
event name) so a record ALWAYS fits its fixed width — the black box records
that something happened even when it cannot record everything about it.

File layout: a 64-byte header (magic, version, record size, capacity, writer
pid, start time) followed by ``capacity`` record slots, preallocated via
``ftruncate`` so the file size is fixed on day one — a short file is itself
evidence of a torn/truncated ring. Reopening an existing ring resumes after
its highest valid seqno: a respawned process appends to the evidence, it
never clobbers a dead predecessor's.

Consumed by ``obs.report --postmortem`` (timeline reconstruction),
``bench_fleet.py`` socket chaos (``flight_records_recovered``) and the
``launch_workers(run_dir=...)`` harvest. Beyond-parity — SURVEY.md §5;
docs/observability.md "The black box and post-mortems".
"""

from __future__ import annotations

import dataclasses
import json
import mmap
import os
import struct
import threading
import time
import zlib
from typing import Any, Dict, List, Mapping, Optional

__all__ = [
    "FLIGHT_PATH_ENV",
    "FlightLog",
    "FlightRecorder",
    "BlackboxLogger",
    "read_flight",
]

#: Env var through which a launcher hands a worker its ring path
#: (``launch_workers(run_dir=...)`` sets it; ``Trainer.fit`` resolves it).
FLIGHT_PATH_ENV = "REPLAY_TPU_FLIGHT_PATH"

MAGIC = b"RPTFLYRC"
VERSION = 1
HEADER = struct.Struct("<8sIIIId")  # magic, version, record_size, capacity, pid, start_unix
HEADER_SIZE = 64  # fixed; HEADER.size padded with zeros
RECORD_HEADER = struct.Struct("<QIHd")  # seqno, crc, length, time
DEFAULT_RECORD_SIZE = 256
DEFAULT_CAPACITY = 2048
_CRC_PREFIX = struct.Struct("<QHd")  # the framed fields under the checksum


def _crc(seqno: int, length: int, when: float, payload: bytes) -> int:
    return zlib.crc32(_CRC_PREFIX.pack(seqno, length, when) + payload) & 0xFFFFFFFF


def _encode_payload(record: Mapping[str, Any], max_len: int) -> bytes:
    """``record`` as compact JSON that fits ``max_len`` bytes.

    Oversized records are whittled, not refused: drop the bulkiest values
    first (the event name and step are kept to the end), then fall back to
    the event name alone — a flight record must always land."""
    items = dict(record)
    encoded = json.dumps(items, separators=(",", ":"), default=str).encode()
    if len(encoded) <= max_len:
        return encoded
    keep_last = ("event", "step", "epoch")
    droppable = sorted(
        (k for k in items if k not in keep_last),
        key=lambda k: len(json.dumps(items[k], default=str)),
        reverse=True,
    )
    for key in droppable:
        del items[key]
        encoded = json.dumps(items, separators=(",", ":"), default=str).encode()
        if len(encoded) <= max_len:
            return encoded
    minimal = {"event": str(record.get("event", "?"))[:64]}
    return json.dumps(minimal, separators=(",", ":")).encode()[:max_len]


class FlightRecorder:
    """Write side of the black box: O(1) in-place ring stores over mmap.

    >>> rec = FlightRecorder("/tmp/doctest.ring", capacity=8)
    >>> rec.record({"event": "on_train_step", "step": 1})
    1
    >>> rec.close()
    """

    def __init__(
        self,
        path: str,
        capacity: int = DEFAULT_CAPACITY,
        record_size: int = DEFAULT_RECORD_SIZE,
    ) -> None:
        if capacity < 1:
            msg = f"capacity must be >= 1, got {capacity}"
            raise ValueError(msg)
        if record_size < RECORD_HEADER.size + 16:
            msg = f"record_size {record_size} leaves no payload room"
            raise ValueError(msg)
        self.path = str(path)
        self._lock = threading.Lock()
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        resumed = self._try_resume()
        if resumed is None:
            self.capacity = int(capacity)
            self.record_size = int(record_size)
            self._seqno = 0
            size = HEADER_SIZE + self.capacity * self.record_size
            with open(self.path, "wb") as fh:
                header = HEADER.pack(
                    MAGIC, VERSION, self.record_size, self.capacity,
                    os.getpid(), time.time(),
                )
                fh.write(header.ljust(HEADER_SIZE, b"\0"))
                fh.truncate(size)
        self._file = open(self.path, "r+b")  # noqa: SIM115 — held for the mmap's life
        self._mm = mmap.mmap(self._file.fileno(), 0)
        self._payload_max = self.record_size - RECORD_HEADER.size

    def _try_resume(self) -> Optional[bool]:
        """Adopt an existing valid ring at :attr:`path` (continue after its
        highest surviving seqno — never clobber a dead process's evidence);
        ``None`` when absent or unusable (then recreated)."""
        try:
            log = read_flight(self.path)
        except (OSError, ValueError):
            return None
        self.capacity = log.capacity
        self.record_size = log.record_size
        self._seqno = log.last_seqno
        return True

    @property
    def last_seqno(self) -> int:
        return self._seqno

    def record(self, record: Mapping[str, Any], when: Optional[float] = None) -> int:
        """Store one record; returns its seqno. O(1): one encode, one CRC,
        one in-place slice store — no syscall beyond the page fault."""
        when = time.time() if when is None else float(when)
        payload = _encode_payload(record, self._payload_max)
        with self._lock:
            if self._mm.closed:  # late event after close: drop, don't raise
                return self._seqno
            self._seqno += 1
            seqno = self._seqno
            frame = RECORD_HEADER.pack(
                seqno, _crc(seqno, len(payload), when, payload), len(payload), when
            )
            offset = HEADER_SIZE + ((seqno - 1) % self.capacity) * self.record_size
            slot = (frame + payload).ljust(self.record_size, b"\0")
            self._mm[offset : offset + self.record_size] = slot
        return seqno

    def flush(self) -> None:
        """A durability point (``msync``): survives machine death up to here.
        Not called per record — the page cache already survives SIGKILL."""
        with self._lock:
            if not self._mm.closed:
                self._mm.flush()

    def close(self) -> None:
        with self._lock:
            if self._mm.closed:
                return
            self._mm.flush()
            self._mm.close()
            self._file.close()

    def __enter__(self) -> "FlightRecorder":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()


@dataclasses.dataclass
class FlightLog:
    """What :func:`read_flight` recovered from a ring.

    ``records`` hold only CRC-verified, JSON-decoded payloads in seqno order
    (each dict gains ``seqno`` and ``t``). ``torn_tail`` is True when any
    written slot failed verification — for a ring whose writer died mid-store
    that is exactly the one in-progress record — or when the file itself was
    truncated below its preallocated size. ``dropped`` counts the rejected
    slots."""

    path: str
    capacity: int
    record_size: int
    writer_pid: int
    start_unix: float
    records: List[Dict[str, Any]]
    last_seqno: int
    torn_tail: bool
    dropped: int
    truncated: bool

    @property
    def recovered(self) -> int:
        return len(self.records)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "path": self.path,
            "capacity": self.capacity,
            "writer_pid": self.writer_pid,
            "start_unix": self.start_unix,
            "recovered": self.recovered,
            "last_seqno": self.last_seqno,
            "torn_tail": self.torn_tail,
            "dropped": self.dropped,
            "truncated": self.truncated,
        }


def read_flight(path: str) -> FlightLog:
    """Recover every verifiable record from a flight ring.

    Raises only for a file that is not a flight ring at all (missing,
    unreadable, bad magic/header — the loud-CLI contract every other
    malformed artifact gets). Damage INSIDE a valid ring — the torn final
    record of a SIGKILLed writer, fuzzed bytes, a truncated tail — never
    raises and never leaks a corrupt record: bad slots are dropped and
    reported via ``torn_tail`` / ``dropped``."""
    with open(path, "rb") as fh:
        raw = fh.read()
    if len(raw) < HEADER.size:
        msg = f"{path}: too short to be a flight ring ({len(raw)} bytes)"
        raise ValueError(msg)
    magic, version, record_size, capacity, pid, start_unix = HEADER.unpack_from(raw)
    if magic != MAGIC:
        msg = f"{path}: not a flight ring (bad magic {magic!r})"
        raise ValueError(msg)
    if version != VERSION:
        msg = f"{path}: flight ring version {version} (reader speaks {VERSION})"
        raise ValueError(msg)
    if record_size < RECORD_HEADER.size + 1 or capacity < 1:
        msg = f"{path}: nonsense ring geometry ({capacity}×{record_size})"
        raise ValueError(msg)

    expected = HEADER_SIZE + capacity * record_size
    truncated = len(raw) < expected
    payload_max = record_size - RECORD_HEADER.size
    by_seqno: Dict[int, Dict[str, Any]] = {}
    dropped = 0
    for slot in range(capacity):
        offset = HEADER_SIZE + slot * record_size
        chunk = raw[offset : offset + record_size]
        if not chunk:
            break  # truncated before this slot: nothing was ever here to judge
        padded = chunk.ljust(record_size, b"\0")
        if padded == b"\0" * record_size:
            continue  # genuinely empty: the writer never reached this slot
        seqno, crc, length, when = RECORD_HEADER.unpack_from(padded)
        payload = padded[RECORD_HEADER.size : RECORD_HEADER.size + length]
        if (
            seqno == 0
            or length > payload_max
            or len(chunk) < RECORD_HEADER.size + length  # frame ran past the cut
            or _crc(seqno, length, when, payload) != crc
        ):
            dropped += 1
            continue
        try:
            decoded = json.loads(payload.decode())
        except (ValueError, UnicodeDecodeError):
            dropped += 1
            continue
        if not isinstance(decoded, dict):
            dropped += 1
            continue
        decoded["seqno"] = seqno
        decoded["t"] = when
        # two valid frames claiming one seqno cannot happen from this writer;
        # if fuzzing manufactures one, keep the first deterministic winner
        by_seqno.setdefault(seqno, decoded)
    records = [by_seqno[s] for s in sorted(by_seqno)]
    return FlightLog(
        path=str(path),
        capacity=capacity,
        record_size=record_size,
        writer_pid=pid,
        start_unix=start_unix,
        records=records,
        last_seqno=max(by_seqno) if by_seqno else 0,
        torn_tail=dropped > 0 or truncated,
        dropped=dropped,
        truncated=truncated,
    )


# -- the RunLogger bridge ---------------------------------------------------- #
# Per-family payload fields worth their bytes in a 256-byte record. Everything
# else a payload carries is kept only if the record still fits (the encoder
# whittles largest-first), so a fat on_fit_end summary degrades gracefully to
# its scalars while a lean on_train_step keeps everything.
_PRIORITY_FIELDS = (
    "loss", "grad_norm", "samples_per_second", "lr",
    "reason", "signal", "preempted", "exhausted",
    "kind", "rows", "fill", "queue_wait_ms", "lane", "served_by",
    "from", "to", "state", "live", "queued", "error_rate",
    "generation", "fraction", "decision", "replica", "status",
    "process_index", "step_in_epoch", "mid_epoch", "count",
)


class BlackboxLogger:
    """A :class:`~replay_tpu.obs.events.RunLogger` sink over a flight ring.

    Attaching it to an existing event fan-out is the whole integration: every
    family the trainer and the scoring service already emit (train step,
    anomaly, health, serve batch, shed/breaker/degrade, heartbeat,
    swap/promotion, SLO) arrives at :meth:`log_event` and becomes one fixed-
    width flight record. Scalars ride along; bulky payloads (telemetry
    summaries, compile reports) are whittled to fit — the black box's job is
    the last N seconds of WHAT HAPPENED, not the full artifact."""

    def __init__(
        self,
        path: str,
        capacity: int = DEFAULT_CAPACITY,
        record_size: int = DEFAULT_RECORD_SIZE,
        meta: Optional[Mapping[str, Any]] = None,
    ) -> None:
        self.recorder = FlightRecorder(path, capacity=capacity, record_size=record_size)
        if meta:
            self.recorder.record({"event": "flight_open", **dict(meta)})

    @property
    def path(self) -> str:
        return self.recorder.path

    def log_event(self, event) -> None:
        payload = event.payload or {}
        record: Dict[str, Any] = {"event": event.event}
        if event.step is not None:
            record["step"] = event.step
        if event.epoch is not None:
            record["epoch"] = event.epoch
        for key in _PRIORITY_FIELDS:
            if key in payload:
                record[key] = _scalar(payload[key])
        for key, value in payload.items():
            if key not in record:
                record[key] = _scalar(value)
        self.recorder.record(record, when=event.time)

    def close(self) -> None:
        self.recorder.close()

    def __enter__(self) -> "BlackboxLogger":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()


def _scalar(value: Any) -> Any:
    """Payload values for the ring: scalars pass (numpy/jax zero-dim scalars
    coerce through ``float()``), containers shrink to a stable short form —
    never multi-KB blobs."""
    if value is None or isinstance(value, (int, float, bool, str)):
        return value
    if isinstance(value, Mapping):
        return f"<{len(value)} keys>"
    if isinstance(value, (list, tuple, set)):
        return f"<{len(value)} items>"
    try:
        return float(value)
    except (TypeError, ValueError):
        return str(value)[:64]
