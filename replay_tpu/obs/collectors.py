"""Telemetry collectors: step timing, jit-retrace counting, device memory.

Beyond-parity (the reference delegates run metering to Lightning and has no
retrace/memory story at all — SURVEY.md §5). :class:`StepTelemetry`
generalizes ``utils/profiling.StepTimer`` (which remains as the minimal
bench-style timer); :class:`CompileTracker` makes the static-shapes invariant
of CLAUDE.md observable instead of aspirational; :class:`MemoryMonitor` snaps
``Device.memory_stats()`` per chip.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Any, Callable, Dict, Optional


class StepTelemetry:
    """Steady-state *and* instantaneous step timing.

    Call :meth:`mark` once before the first step, then :meth:`tick` after each
    step (or each observed group of ``steps`` steps); each tick returns the
    instantaneous rates since the previous mark/tick. :meth:`summary` returns
    the steady-state record — shape-stable: the same keys come back whether or
    not anything was measured (NaN-filled), so JSONL consumers never KeyError.

    ``warmup_steps`` ticks are excluded from the steady-state window (compile
    happens on the first step); pass a device ``result`` to fence with
    ``block_until_ready`` when the caller has not already synchronized.
    """

    def __init__(self, warmup_steps: int = 1, samples_per_step: Optional[int] = None) -> None:
        self.warmup_steps = max(int(warmup_steps), 0)
        self.samples_per_step = samples_per_step
        self._count = 0
        self._last: Optional[float] = None
        self._start: Optional[float] = None
        self._measured_samples = 0.0
        self._measured_steps = 0

    @staticmethod
    def _fence(result: Any) -> None:
        if result is not None:
            import jax

            jax.block_until_ready(result)

    def mark(self, result: Any = None) -> None:
        """Set the reference point for the next tick's instantaneous rate.

        Re-marking after a pause (validation, checkpointing) RESUMES the
        steady-state window: the gap since the last tick is discounted, so
        non-training wall time never dilutes the summary rates.
        """
        self._fence(result)
        now = time.perf_counter()
        if self._start is not None and self._last is not None:
            self._start += now - self._last
        self._last = now

    def tick(self, result: Any = None, samples: Optional[float] = None, steps: int = 1) -> Dict[str, float]:
        """Record ``steps`` completed steps totalling ``samples`` samples."""
        self._fence(result)
        now = time.perf_counter()
        nan = float("nan")
        if samples is None and self.samples_per_step is not None:
            samples = self.samples_per_step * steps
        before_count, before_time = self._count, self._last
        self._count += steps
        record = {
            "step": float(self._count),
            "step_seconds": nan,
            "steps_per_sec": nan,
            "samples_per_sec": nan,
        }
        if before_time is not None:
            elapsed = now - before_time
            if elapsed > 0:
                record["step_seconds"] = elapsed / steps
                record["steps_per_sec"] = steps / elapsed
                if samples is not None:
                    record["samples_per_sec"] = samples / elapsed
        self._last = now
        if self._count <= self.warmup_steps:
            # still inside warmup: the steady-state clock starts at this
            # tick's END (its wall time includes compile)
            self._start = now
        else:
            measured = min(steps, self._count - self.warmup_steps)
            frac = measured / steps
            if measured < steps:
                # the tick spans the warmup boundary: prorate its window so
                # the post-warmup portion is neither discarded (NaN summaries
                # on short runs) nor counted against zero elapsed (inflation)
                if before_time is not None and now > before_time:
                    self._start = now - (now - before_time) * frac
                else:
                    self._start, measured, frac = now, 0, 0.0
            elif self._start is None:
                # warmup_steps=0: the window is anchored at mark() time; a
                # tick with no anchor at all has no time base and is dropped
                if before_time is not None:
                    self._start = before_time
                else:
                    self._start, measured, frac = now, 0, 0.0
            self._measured_steps += measured
            if samples:
                self._measured_samples += samples * frac
        return record

    def summary(self, result: Any = None) -> Dict[str, float]:
        """Steady-state record over every post-warmup tick (shape-stable)."""
        self._fence(result)
        nan = float("nan")
        record = {
            "steps": float(self._measured_steps),
            "elapsed_seconds": nan,
            "steps_per_sec": nan,
            "samples_per_sec": nan,
        }
        if self._start is not None and self._measured_steps > 0:
            # the window ends at the LAST TICK, not at this call: summary()
            # typically runs after validation/checkpointing whose wall time
            # must not dilute the steady-state training rate
            end = self._last if self._last is not None else time.perf_counter()
            elapsed = end - self._start
            if elapsed > 0:
                record["elapsed_seconds"] = elapsed
                record["steps_per_sec"] = self._measured_steps / elapsed
                if self._measured_samples:
                    record["samples_per_sec"] = self._measured_samples / elapsed
        return record


class CompileTracker:
    """Counts jit cache misses (traces) per function and compile wall-time.

    :meth:`wrap` the *python* step function before handing it to ``jax.jit``:
    every retrace executes the python body once, so the wrapper's counter is
    exactly the number of compiled programs XLA built for that name. Pair the
    dispatch call with :meth:`observe` to attribute wall-clock to compilation
    (jit traces + compiles synchronously inside the triggering call).

    Under the static-shapes convention (CLAUDE.md) a healthy training run
    shows ``traces == 1`` per jitted function; anything higher is a shape leak.
    """

    def __init__(self) -> None:
        self._traces: Dict[str, int] = {}
        self._compile_seconds: Dict[str, float] = {}

    def wrap(self, fn: Callable, name: Optional[str] = None) -> Callable:
        label = name or getattr(fn, "__name__", "fn")
        self._traces.setdefault(label, 0)

        @functools.wraps(fn)
        def traced(*args, **kwargs):
            self._traces[label] = self._traces.get(label, 0) + 1
            return fn(*args, **kwargs)

        return traced

    @contextlib.contextmanager
    def observe(self, name: str):
        """Attribute the enclosed call's wall time to compilation iff a trace
        of ``name`` happened inside it (first call / retrace)."""
        before = self._traces.get(name, 0)
        start = time.perf_counter()
        try:
            yield
        finally:
            if self._traces.get(name, 0) > before:
                elapsed = time.perf_counter() - start
                self._compile_seconds[name] = self._compile_seconds.get(name, 0.0) + elapsed

    @property
    def traces(self) -> Dict[str, int]:
        return dict(self._traces)

    @property
    def compile_seconds(self) -> Dict[str, float]:
        return dict(self._compile_seconds)

    @property
    def total_compile_seconds(self) -> float:
        return float(sum(self._compile_seconds.values()))

    def report(self) -> Dict[str, Dict[str, float]]:
        """{name: {traces, compile_seconds}} over every wrapped function."""
        return {
            name: {
                "traces": count,
                "compile_seconds": round(self._compile_seconds.get(name, 0.0), 4),
            }
            for name, count in sorted(self._traces.items())
        }


class MemoryMonitor:
    """Per-device ``memory_stats()`` snapshots and the cross-device peak.

    CPU backends report no allocator stats (``memory_stats() is None``): every
    accessor then degrades to an empty snapshot / ``None`` peak rather than
    raising, so the same telemetry code runs on the TPU and the CPU-mesh dry
    runs.
    """

    def __init__(self, devices=None) -> None:
        self._devices = devices
        # chunk-boundary sampling (observe()): the windowed high-water mark
        # over explicit samples, vs the allocator's process-lifetime peak
        self.observed_peak_bytes: Optional[int] = None
        self.observed_samples: int = 0

    def _resolve(self):
        if self._devices is None:
            import jax

            self._devices = jax.devices()
        return self._devices

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        stats = {}
        for device in self._resolve():
            try:
                device_stats = device.memory_stats()
            except Exception:  # backends without allocator introspection
                device_stats = None
            if not device_stats:
                continue
            stats[str(device)] = {
                k: float(v)
                for k, v in device_stats.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)
            }
        return stats

    def _max_over_devices(self, key: str) -> Optional[int]:
        values = [s[key] for s in self.snapshot().values() if key in s]
        return int(max(values)) if values else None

    def peak_bytes(self) -> Optional[int]:
        """Max ``peak_bytes_in_use`` over devices (None when unavailable)."""
        return self._max_over_devices("peak_bytes_in_use")

    def bytes_in_use(self) -> Optional[int]:
        return self._max_over_devices("bytes_in_use")

    def observe(self) -> Optional[int]:
        """Sample the current cross-device peak into the observed window.

        The scan-chunked fit calls this at every chunk boundary (the only
        points the host touches the loop anyway), so ``observed_peak_bytes``
        tracks the fit's own HBM high-water mark instead of inheriting an
        earlier program's process-lifetime peak. CPU-safe: backends without
        allocator stats return None and the sample is not counted.
        """
        peak = self.peak_bytes()
        if peak is None:
            return None
        self.observed_samples += 1
        if self.observed_peak_bytes is None or peak > self.observed_peak_bytes:
            self.observed_peak_bytes = peak
        return peak
