"""Event/callback layer: the Lightning logger replacement.

Parity target: replay/nn/lightning delegates run logging to PyTorch Lightning's
``Trainer(logger=...)`` / callback machinery (module.py:14-120); here the
trainer emits :class:`TrainerEvent` records to :class:`RunLogger` sinks.

Event flow emitted by ``replay_tpu.nn.Trainer.fit``::

    on_fit_start
      on_train_step*          (loss, lr, samples_per_sec, step_seconds;
                               + a `health` record every HealthConfig.cadence
                               steps — obs.health. The cadence holds under
                               fit(scan_chunk=K) too: the chunk's [K] metrics
                               fan back out into per-step events)
      on_health_warning*      (HealthWatcher EWMA blowup of grad norm /
                               update ratio, BEFORE the sentinel trips)
      on_anomaly*             (a non-finite step the sentinel skipped:
                               loss, grad_norm, consecutive_bad)
      on_recovery*            (RecoveryPolicy rollback: reason, restored_step,
                               lr_scale, restarts)
      on_validation_end?      (the epoch's metric record, when validating)
      on_epoch_end            (the full history record)
      on_checkpoint?          (every checkpoint save, incl. mid-epoch)
      on_preemption?          (SIGTERM/SIGINT honored: checkpoint saved,
                               fit exits cleanly for resume=True)
    on_fit_end                (telemetry summary, compile report, peak memory,
                               sentinel bad_steps total)

The serving stack (``replay_tpu.serve.ScoringService``) reuses the same sinks
with its own event family::

    on_serve_start            (mode, bucket ladders, max_wait, cache capacity,
                               queue-depth bound, default deadline)
      on_serve_batch*         (one per dispatched micro-batch: lane, rows,
                               bucket, fill, max queue wait, dropped
                               expired/cancelled counts)
      on_shed*                (admission control refused work: lane, depth,
                               retry-after hint; throttled, carries the
                               coalesced `count` per emit)
      on_breaker*             (circuit-breaker transition: from/to state,
                               consecutive failures — one per transition)
      on_degrade*             (traffic rerouted down the degradation ladder:
                               to cache_only/fallback, reason; throttled)
      on_quality_window*      (obs.quality: one per role per closed window —
                               coverage, novelty, surprisal, popularity,
                               intra-list diversity, score entropy/margin,
                               online prequential hitrate/MRR/NDCG and the
                               PSI drift state)
      on_drift_warning*       (PSI crossed the drift threshold on some series;
                               latched — one warning per excursion, throttled)
    on_serve_end              (request totals, cache hit rate, batch fill
                               ratio, queue-wait stats, shed/deadline-miss/
                               degradation totals, breaker stats, serve
                               goodput)

and the fleet router (``serve/fleet.py``) one level above that::

    on_fleet_start            (replica ids, vnodes, hedge/backoff config)
      on_replica_health*      (one per health transition: replica, from, to,
                               reason — heartbeat/gauge driven)
      on_failover*            (a replica declared dead: replica, reason,
                               ~fraction of users rerouted)
      on_hedge*               (a slow request raced on a second replica:
                               user, primary, hedge target)
    on_fleet_end              (request/reroute/retry/hedge totals, per-replica
                               routing counts, router-observed p50/p99)

Every event flattens to one JSON-able dict (``event`` + ``time`` + optional
``step``/``epoch`` + the payload), so a run directory's ``events.jsonl`` is a
self-describing artifact shared by training runs, ``bench.py`` /
``bench_serve.py`` records and the CPU-mesh dry runs.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Mapping, Optional, Sequence

logger = logging.getLogger("replay_tpu")


def _jsonable(value: Any) -> Any:
    """Coerce numpy / jax scalars and containers into plain, STRICT JSON
    types. Non-finite floats become null: shape-stable keys survive, and the
    emitted lines stay valid RFC-8259 JSON (the bare ``NaN`` token is not)."""
    if isinstance(value, Mapping):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if isinstance(value, float):
        return value if math.isfinite(value) else None
    if isinstance(value, (str, bool, int)) or value is None:
        return value
    # numpy / jax scalars and 0-d arrays expose item(); arrays expose tolist()
    if hasattr(value, "item") and getattr(value, "ndim", None) in (0, None):
        try:
            return _jsonable(value.item())
        except (TypeError, ValueError):
            pass
    if hasattr(value, "tolist"):
        try:
            return _jsonable(value.tolist())
        except (TypeError, ValueError):
            pass
    return str(value)


@dataclass
class TrainerEvent:
    """One observation from a training run.

    ``payload`` keys flatten into the record next to ``event``/``time``/
    ``step``/``epoch``, so consumers index events.jsonl lines by plain keys.
    """

    event: str
    step: Optional[int] = None
    epoch: Optional[int] = None
    time: float = field(default_factory=time.time)
    payload: Dict[str, Any] = field(default_factory=dict)

    def to_record(self) -> Dict[str, Any]:
        record: Dict[str, Any] = {"event": self.event, "time": self.time}
        if self.step is not None:
            record["step"] = int(self.step)
        if self.epoch is not None:
            record["epoch"] = int(self.epoch)
        for key, value in self.payload.items():
            record[str(key)] = _jsonable(value)
        return record


class RunLogger:
    """Protocol for event sinks. Subclasses implement :meth:`log_event`;
    :meth:`close` is optional (flush/teardown). Usable as a context manager."""

    def log_event(self, event: TrainerEvent) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __enter__(self) -> "RunLogger":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()


class JsonlLogger(RunLogger):
    """One JSON line per event, appended to ``run_dir/filename``.

    Lines are flushed as written so a crashed run keeps its telemetry. The
    same sink doubles as a raw-record writer (:meth:`log_record`) for driver
    artifacts like ``BENCH_TPU_SIDECAR.json`` that are single records rather
    than event streams (``mode="w"``).

    Thread-safe: the serve stack emits from client threads (``on_shed``/
    ``on_breaker``) concurrently with the worker's ``on_serve_batch``, so each
    line is serialized first and written in one locked call — concurrent
    emits can interleave lines, never tear one.

    Bounded growth (week-long runs, the serving service): ``max_bytes``
    enables size-based rotation — when appending a line would push the file
    past the bound, ``events.jsonl`` rotates to ``events.jsonl.1`` (existing
    backups shift up, the oldest beyond ``rotate`` is dropped) and a fresh
    file continues the stream. ``obs.report`` reads the rotated shards oldest-
    first, so a rotated run still summarizes as one stream (minus whatever the
    bound evicted). A single record is never split across shards.

    Multi-host runs: pass this process's ``process_index`` and non-zero
    processes write ``events.p<i>.jsonl`` next to process 0's ``events.jsonl``
    — the shard layout ``obs.report`` merges into one cross-host report (each
    record additionally carries its ``process_index`` stamp).
    """

    def __init__(
        self,
        run_dir: str,
        filename: str = "events.jsonl",
        mode: str = "a",
        max_bytes: Optional[int] = None,
        rotate: int = 3,
        process_index: Optional[int] = None,
    ) -> None:
        self.run_dir = str(run_dir)
        os.makedirs(self.run_dir, exist_ok=True)
        if process_index:
            root, ext = os.path.splitext(filename)
            filename = f"{root}.p{int(process_index)}{ext}"
        self.path = os.path.join(self.run_dir, filename)
        if max_bytes is not None and max_bytes < 1:
            msg = "max_bytes must be a positive byte bound (or None)"
            raise ValueError(msg)
        if rotate < 1:
            msg = "rotate must keep at least one backup shard"
            raise ValueError(msg)
        self.max_bytes = max_bytes
        self.rotate = int(rotate)
        self._fh = open(self.path, mode)
        self._lock = threading.Lock()

    def _rotate_locked(self) -> None:
        """Shift ``path.(i)`` → ``path.(i+1)`` (oldest dropped) and reopen a
        fresh base file. Caller holds the lock."""
        self._fh.close()
        for index in range(self.rotate - 1, 0, -1):
            source = f"{self.path}.{index}"
            if os.path.exists(source):
                os.replace(source, f"{self.path}.{index + 1}")
        os.replace(self.path, f"{self.path}.1")
        self._fh = open(self.path, "a")

    def log_record(self, record: Mapping[str, Any]) -> None:
        line = json.dumps(_jsonable(record), allow_nan=False) + "\n"
        with self._lock:
            if (
                self.max_bytes is not None
                and self._fh.tell() > 0
                and self._fh.tell() + len(line) > self.max_bytes
            ):
                self._rotate_locked()
            self._fh.write(line)
            self._fh.flush()

    def log_event(self, event: TrainerEvent) -> None:
        self.log_record(event.to_record())

    def close(self) -> None:
        if not self._fh.closed:
            self._fh.close()


def _load_summary_writer():
    """Resolve a TensorBoard SummaryWriter class, or None when no backend is
    installed (tensorboardX, then torch's bundled writer)."""
    try:
        from tensorboardX import SummaryWriter

        return SummaryWriter
    except ImportError:
        pass
    try:
        from torch.utils.tensorboard import SummaryWriter

        return SummaryWriter
    except ImportError:
        return None


class TensorBoardLogger(RunLogger):
    """Scalar + histogram writer over an optional TensorBoard backend.

    Missing backend → a warning once, then every call is a no-op: attaching
    this logger can never break a training run (the optional-dependency rule
    of utils/types.py applied to observability). ``health`` payloads
    (obs.health) are routed specially: scalar leaves become ``health/...``
    scalars, vector leaves (per-head attention entropies) become real
    histograms via :meth:`log_histogram`.
    """

    def __init__(self, log_dir: str) -> None:
        self.log_dir = str(log_dir)
        writer_cls = _load_summary_writer()
        if writer_cls is None:
            logger.warning(
                "TensorBoardLogger: no tensorboard backend installed "
                "(tensorboardX or torch); events will be dropped"
            )
            self._writer = None
        else:
            self._writer = writer_cls(self.log_dir)

    @staticmethod
    def _scalars(payload: Mapping[str, Any]):
        """Numeric payload entries, flattening one dict level — the trainer
        nests epoch/validation metrics under a ``record`` key."""
        for key, value in payload.items():
            if isinstance(value, Mapping):
                for sub_key, sub_value in value.items():
                    if not isinstance(sub_value, bool) and isinstance(sub_value, (int, float)):
                        yield f"{key}/{sub_key}", sub_value
            elif not isinstance(value, bool) and isinstance(value, (int, float)):
                yield key, value

    def log_histogram(self, tag: str, values: Any, step: int = 0) -> None:
        """Write one histogram; a no-op when no backend (or an ancient writer
        without ``add_histogram``) is installed — same never-break contract
        as the scalar path."""
        if self._writer is None or not hasattr(self._writer, "add_histogram"):
            return
        import numpy as np

        array = np.asarray(values, dtype=np.float64).reshape(-1)
        array = array[np.isfinite(array)]
        if array.size:
            self._writer.add_histogram(tag, array, global_step=int(step))

    def _log_health(self, health: Mapping[str, Any], step: int) -> None:
        from .health import flatten_health

        for tag, value in flatten_health(health).items():
            if isinstance(value, (list, tuple)):
                self.log_histogram(tag, value, step)
            elif not isinstance(value, bool) and isinstance(value, (int, float)):
                self._writer.add_scalar(tag, float(value), global_step=step)

    def log_event(self, event: TrainerEvent) -> None:
        if self._writer is None:
            return
        step = int(event.step) if event.step is not None else 0
        # `health` is routed whole through _log_health (scalars + histograms);
        # letting _scalars flatten it too would double-log its top level
        payload = {k: v for k, v in event.payload.items() if k != "health"}
        for key, value in self._scalars(payload):
            tag = key if event.event == "on_train_step" else f"{event.event}/{key}"
            self._writer.add_scalar(tag, float(value), global_step=step)
        health = event.payload.get("health")
        if isinstance(health, Mapping) and event.event == "on_train_step":
            # epoch-end events repeat the last fetched record — logging it
            # again would double-count the histogram timeline
            self._log_health(health, step)

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()


class MultiLogger(RunLogger):
    """Fan one event stream out to several sinks."""

    def __init__(self, loggers: Iterable[RunLogger]) -> None:
        self.loggers: Sequence[RunLogger] = tuple(loggers)

    def log_event(self, event: TrainerEvent) -> None:
        for sink in self.loggers:
            sink.log_event(event)

    def close(self) -> None:
        for sink in self.loggers:
            sink.close()


class ConsoleLogger(RunLogger):
    """The old ``log_every`` print path, rebuilt on the event stream: every
    ``every``-th *received* train-step event and every epoch record go to the
    python logger (the trainer pre-filters the stream to the requested cadence
    when the console is the only sink, so counting received events is exact)."""

    def __init__(self, every: int = 100) -> None:
        self.every = max(int(every), 1)
        self._seen = 0

    def log_event(self, event: TrainerEvent) -> None:
        if event.event == "on_train_step":
            self._seen += 1
            if self._seen % self.every == 0:
                logger.info(
                    "epoch %s step %s loss %.4f",
                    event.epoch,
                    event.step,
                    event.payload.get("loss", float("nan")),
                )
        elif event.event == "on_health_warning":
            logger.warning(
                "health warning at step %s: %s blew up to %.3g (%.1fx its EWMA %.3g)",
                event.step,
                event.payload.get("signal"),
                event.payload.get("value", float("nan")),
                event.payload.get("factor", float("nan")),
                event.payload.get("ewma", float("nan")),
            )
        elif event.event == "on_anomaly":
            logger.warning(
                "anomaly at step %s: non-finite loss/grads, update skipped "
                "(%s consecutive)",
                event.step,
                event.payload.get("consecutive_bad"),
            )
        elif event.event == "on_recovery":
            logger.warning(
                "recovery (%s): rolled back to step %s, lr scale %s, restart %s",
                event.payload.get("reason"),
                event.payload.get("restored_step"),
                event.payload.get("lr_scale"),
                event.payload.get("restarts"),
            )
        elif event.event == "on_preemption":
            logger.warning(
                "preemption (%s) at step %s: checkpoint saved, exiting",
                event.payload.get("signal"),
                event.step,
            )
        elif event.event == "on_slo_violation":
            logger.warning(
                "SLO violation [%s] at step %s: %s = %.4g (breached %s %.4g, "
                "%s consecutive)",
                event.payload.get("rule"),
                event.step,
                event.payload.get("metric"),
                event.payload.get("value", float("nan")),
                event.payload.get("op"),
                event.payload.get("threshold", float("nan")),
                event.payload.get("consecutive"),
            )
        elif event.event == "on_slo_recovery":
            logger.info(
                "SLO recovered [%s] at step %s: %s = %.4g after %.2fs in breach "
                "(%s evaluation(s))",
                event.payload.get("rule"),
                event.step,
                event.payload.get("metric"),
                event.payload.get("value", float("nan")),
                event.payload.get("breach_seconds", float("nan")),
                event.payload.get("breached_evaluations"),
            )
        elif event.event == "on_shed":
            logger.warning(
                "overload: %s request(s) shed on lane %s (depth %s/%s)",
                event.payload.get("count", 1),
                event.payload.get("lane"),
                event.payload.get("depth"),
                event.payload.get("max_depth"),
            )
        elif event.event == "on_breaker":
            logger.warning(
                "circuit breaker %s -> %s (%s consecutive failure(s))",
                event.payload.get("from"),
                event.payload.get("to"),
                event.payload.get("consecutive_failures"),
            )
        elif event.event == "on_degrade":
            logger.warning(
                "degraded: %s request(s) rerouted to %s (%s)",
                event.payload.get("count", 1),
                event.payload.get("to"),
                event.payload.get("reason"),
            )
        elif event.event == "on_replica_health":
            to_state = event.payload.get("to")
            emit = logger.warning if to_state in ("degraded", "dead") else logger.info
            emit(
                "fleet replica %s: %s -> %s (%s)",
                event.payload.get("replica"),
                event.payload.get("from"),
                to_state,
                event.payload.get("reason"),
            )
        elif event.event == "on_failover":
            logger.warning(
                "fleet failover: replica %s dead (%s) — ~%.0f%% of users "
                "rerouted along the ring",
                event.payload.get("replica"),
                event.payload.get("reason"),
                100.0 * (event.payload.get("users_fraction") or 0.0),
            )
        elif event.event == "on_hedge":
            logger.warning(
                "fleet hedge: user %s slow on %s — racing %s",
                event.payload.get("user_id"),
                event.payload.get("primary"),
                event.payload.get("hedge"),
            )
        elif event.event == "on_fleet_start":
            logger.info(
                "fleet up: %s replica(s) %s (vnodes=%s, hedge_ms=%s, "
                "max_retries=%s)",
                len(event.payload.get("replicas") or ()),
                event.payload.get("replicas"),
                event.payload.get("vnodes"),
                event.payload.get("hedge_ms"),
                event.payload.get("max_retries"),
            )
        elif event.event == "on_fleet_end":
            logger.info(
                "fleet down: %s request(s) on %s replica(s) — %s rerouted, "
                "%s retried, %s hedged (%s won), p99 %.1f ms",
                event.payload.get("requests"),
                event.payload.get("replicas"),
                event.payload.get("reroutes"),
                event.payload.get("retries"),
                event.payload.get("hedges"),
                event.payload.get("hedge_wins"),
                event.payload.get("p99_ms") or 0.0,
            )
        elif event.event == "on_swap":
            logger.info(
                "weight swap (%s): generation %s -> %s%s",
                event.payload.get("reason"),
                event.payload.get("from_generation"),
                event.payload.get("to_generation"),
                " [recompiled]" if event.payload.get("recompiled") else "",
            )
        elif event.event == "on_promotion":
            logger.info(
                "canary PROMOTED: generation %s (from %s) after %s clean "
                "evaluation(s)",
                event.payload.get("generation"),
                event.payload.get("from_generation"),
                event.payload.get("clean_evals"),
            )
        elif event.event == "on_rollback":
            logger.warning(
                "canary ROLLED BACK: generation %s -> %s (rules: %s)",
                event.payload.get("generation"),
                event.payload.get("restored_generation"),
                ", ".join(event.payload.get("rules") or []) or "<manual>",
            )
        elif event.event == "on_quality_window":
            drift = event.payload.get("drift") or {}
            logger.info(
                "quality[%s] @%s req: hitrate@%s %.4f (cum %.4f, %s joins), "
                "coverage %.3f, novelty %.3f, surprisal %.3f, ild %.3f, "
                "drift psi %.3f",
                event.payload.get("role"),
                event.payload.get("requests"),
                event.payload.get("k"),
                event.payload.get("online_hitrate") or 0.0,
                event.payload.get("online_hitrate_cum") or 0.0,
                event.payload.get("joins"),
                event.payload.get("coverage") or 0.0,
                event.payload.get("novelty") or 0.0,
                event.payload.get("surprisal") or 0.0,
                event.payload.get("ild") or 0.0,
                (drift.get("max") if isinstance(drift, Mapping) else None) or 0.0,
            )
        elif event.event == "on_drift_warning":
            logger.warning(
                "DRIFT: psi %.3f on %s series crossed %.2f (max %.3f) — "
                "serving distribution shifted",
                event.payload.get("psi") or 0.0,
                event.payload.get("series"),
                event.payload.get("threshold") or 0.0,
                event.payload.get("psi_max") or 0.0,
            )
        elif event.event == "on_epoch_end":
            logger.info("epoch %s: %s", event.epoch, event.payload.get("record"))
        elif event.event == "on_serve_end":
            logger.info(
                "serve complete: %s request(s), cache hit rate %.1f%%, "
                "batch fill %.1f%%, mean queue wait %.2f ms",
                event.payload.get("requests"),
                100.0 * (event.payload.get("cache_hit_rate") or 0.0),
                100.0 * (event.payload.get("batch_fill_ratio") or 0.0),
                event.payload.get("queue_wait_ms_mean") or 0.0,
            )
        elif event.event == "on_fit_end":
            summary = {
                k: event.payload.get(k)
                for k in ("telemetry", "compile", "peak_memory_bytes")
                if k in event.payload
            }
            logger.info("fit complete: %s", summary)
            device_time = event.payload.get("device_time")
            if isinstance(device_time, Mapping) and device_time.get("scopes"):
                logger.info(
                    "device attribution: %s",
                    " ".join(
                        f"{scope}={100.0 * float(entry.get('fraction', 0.0)):.1f}%"
                        for scope, entry in device_time["scopes"].items()
                        if isinstance(entry, Mapping)
                    ),
                )
