"""Scrapeable metrics endpoint: stdlib HTTP, Prometheus text + JSON snapshot.

The network half of the live metrics plane (:mod:`replay_tpu.obs.metrics`).
One daemon thread runs a ``ThreadingHTTPServer`` (each scrape is answered on
its own short-lived thread, so a slow scraper never blocks the next one):

* ``GET /metrics``  — Prometheus text exposition format, rendered in one pass
  under the registry lock (no torn lines, counters monotone across scrapes);
* ``GET /snapshot`` — the full registry as JSON, including histogram quantile
  estimates (the artifact CI uploads), plus a reserved ``__identity__`` block
  (``process_index``/``pid``/``start_unix``) so a federation scraper
  (``obs.federate``) or a post-mortem can label and age every scrape;
* ``GET /healthz``  — liveness probe. Plain ``ok`` by default (the shape
  existing probes assert on); with ``?format=json`` or an
  ``Accept: application/json`` header it returns the structured health
  document a remote fleet monitor needs to drive ``ReplicaHealth`` from a
  pure scrape — the live bit, lane depth vs the configured bound, breaker
  state and the windowed error-rate inputs — produced by the
  ``health_source`` callable (e.g. ``ScoringService.heartbeat``). Without a
  source the JSON document is just ``{"live": true}``; a raising source
  answers 503 with ``{"live": false, "error": ...}`` rather than hiding the
  failure behind a happy 200.

Failure posture: a metrics endpoint must never take down what it observes.
A busy port (or any bind error) logs one warning and degrades the exporter
to a no-op — ``port`` is then ``None`` and :meth:`MetricsExporter.close` is
safe to call regardless. ``port=0`` binds an ephemeral port (tests, and
multi-process runs where a fixed port would collide on one host) and
exposes the chosen one via :attr:`MetricsExporter.port`.

Started/stopped by ``Trainer.fit(metrics_port=...)`` and
``ScoringService(metrics_port=...)``; usable standalone around any
:class:`~replay_tpu.obs.metrics.MetricsRegistry`.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional

from .metrics import MetricsRegistry

logger = logging.getLogger("replay_tpu")

__all__ = ["MetricsExporter"]

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(BaseHTTPRequestHandler):
    # the registry is attached to the server instance by MetricsExporter
    server: "_Server"

    def _respond(self, status: int, content_type: str, body: bytes) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler contract
        try:
            path, _, query = self.path.partition("?")
            if path in ("/metrics", "/"):
                body = self.server.registry.render_prometheus().encode()
                self._respond(200, PROMETHEUS_CONTENT_TYPE, body)
            elif path == "/snapshot":
                snapshot = self.server.registry.snapshot()
                # identity rides under a reserved non-metric key so the
                # federation scraper and the post-mortem report can label and
                # age every scrape without a second round trip
                snapshot["__identity__"] = self.server.identity
                body = json.dumps(snapshot, indent=2, default=str).encode()
                self._respond(200, "application/json", body)
            elif path == "/healthz":
                wants_json = "format=json" in query or "application/json" in (
                    self.headers.get("Accept") or ""
                )
                if wants_json:
                    self._respond_health_json()
                else:
                    self._respond(200, "text/plain", b"ok\n")
            else:
                self._respond(404, "text/plain", b"not found\n")
        except (BrokenPipeError, ConnectionResetError):
            pass  # the scraper hung up mid-response; nothing to salvage

    def _respond_health_json(self) -> None:
        source = self.server.health_source
        try:
            health: Dict[str, Any] = {"live": True}
            if source is not None:
                health = dict(source())
        except Exception as exc:  # noqa: BLE001 — a broken source IS the signal
            body = json.dumps(
                {"live": False, "error": repr(exc), **self.server.identity}
            ).encode()
            self._respond(503, "application/json", body)
            return
        for key, value in self.server.identity.items():
            health.setdefault(key, value)  # the source's own fields win
        body = json.dumps(health, default=str).encode()
        self._respond(200, "application/json", body)

    def log_message(self, format: str, *args) -> None:  # noqa: A002
        pass  # scrape-cadence request lines must not spam the run's stderr


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    # a scrape can race a restart on the same port in tests: reuse avoids
    # TIME_WAIT flakes without masking a genuinely-owned port (bind on a port
    # another LISTENING server holds still fails)
    allow_reuse_address = True
    registry: MetricsRegistry
    health_source: Optional[Callable[[], Dict[str, Any]]]
    identity: Dict[str, Any]


class MetricsExporter:
    """Serve a registry over HTTP from a background daemon thread.

    >>> registry = MetricsRegistry()
    >>> exporter = MetricsExporter(registry, port=0).start()
    >>> exporter.port is not None  # ephemeral port bound
    True
    >>> exporter.close()
    """

    def __init__(
        self,
        registry: MetricsRegistry,
        port: int = 9100,
        host: str = "127.0.0.1",
        health_source: Optional[Callable[[], Dict[str, Any]]] = None,
        identity: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.registry = registry
        self.requested_port = int(port)
        self.host = host
        self.health_source = health_source
        # who answers this port: the identity block /snapshot and /healthz
        # carry so a federation scraper (or a post-mortem) can label every
        # scrape with the process it came from and age it by start time
        self.identity: Dict[str, Any] = {
            "process_index": int(os.environ.get("REPLAY_TPU_PROCESS_ID", 0) or 0),
            "pid": os.getpid(),
            "start_unix": time.time(),
        }
        if identity:
            self.identity.update(identity)
        self._server: Optional[_Server] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        """The bound port, or ``None`` when the exporter is not serving
        (never started, bind failed, or closed)."""
        return self._server.server_address[1] if self._server is not None else None

    @property
    def url(self) -> Optional[str]:
        bound = self.port
        return f"http://{self.host}:{bound}" if bound is not None else None

    def start(self) -> "MetricsExporter":
        if self._server is not None:
            return self
        try:
            server = _Server((self.host, self.requested_port), _Handler)
        except OSError as exc:
            # the no-op degradation: a second trainer on the host, a stale
            # process holding the port — the run continues unobserved rather
            # than dead
            logger.warning(
                "metrics exporter: cannot bind %s:%s (%s); metrics will not be served",
                self.host, self.requested_port, exc,
            )
            return self
        server.registry = self.registry
        server.health_source = self.health_source
        server.identity = self.identity
        self._server = server
        self._thread = threading.Thread(
            target=server.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="metrics-exporter",
            daemon=True,
        )
        self._thread.start()
        logger.info("metrics exporter serving on %s", self.url)
        return self

    def set_registry(self, registry: MetricsRegistry) -> None:
        """Swap the served registry atomically (the federation scraper builds
        a fresh merged registry per pass). In-flight requests finish against
        whichever registry they resolved — both are internally consistent."""
        self.registry = registry
        if self._server is not None:
            self._server.registry = registry

    def close(self) -> None:
        server, thread = self._server, self._thread
        self._server, self._thread = None, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=5.0)

    def __enter__(self) -> "MetricsExporter":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.close()
