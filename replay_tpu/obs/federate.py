"""Fleet-wide metrics federation: N per-process exporters, ONE ``/metrics``.

Multi-process runs leave live metrics scattered: every worker and every
replica server runs its own :class:`~replay_tpu.obs.exporter.MetricsExporter`
on its own ephemeral port, so "how many requests did the FLEET serve" means N
scrapes and hand-merging. This module is the live complement to
``obs.report``'s offline events-shard merge: a :class:`FleetFederator`
scrapes each member's ``/snapshot`` (the JSON view — exact bucket counts, not
the quantile estimates a Prometheus text scrape would force us to re-derive)
and folds everything into one fresh
:class:`~replay_tpu.obs.metrics.MetricsRegistry`, served on a single
federated ``/metrics``.

Merge semantics, per metric kind:

* **counters** — summed across processes. The federated total equals the sum
  of the per-process totals EXACTLY (integer-valued counters add without
  error in float64), so it reconciles against each member's own accounting
  (``ScoringService.stats()``) the way PR 10 reconciles ``shed_total``.
* **gauges** — last-write-wins scalars do not add; each process's value is
  kept as its own series, labeled ``process="<index>"`` (the exporter's
  identity block names the index; the scrape order is the fallback).
* **histograms** — bucket-merged losslessly: same bounds ⇒ per-bucket counts,
  overflow, count, sum added; min/max folded. Mismatched bounds for the same
  metric are a configuration error and raise :class:`FederationError` naming
  the metric — silently resampling would fake precision. Quantiles are then
  re-estimated over the MERGED counts (estimating over per-process quantiles
  is the classic averaging-percentiles mistake).

A member that fails to answer is recorded in ``errors`` and skipped — the
federated view degrades to the reachable subset rather than erroring the
whole scrape; the ``replay_federation_members`` /
``replay_federation_errors_total`` meta-series make the coverage visible.

Stdlib-only by contract (urllib + the registry), like the exporter it feeds:
``python -m replay_tpu.obs.federate http://h:p1 http://h:p2 --port 9200``
runs it standalone. Beyond-parity — SURVEY.md §5; docs/observability.md
"The black box and post-mortems" (federation quickstart).
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
import urllib.request
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from .metrics import Histogram, MetricsRegistry
from .exporter import MetricsExporter

logger = logging.getLogger("replay_tpu")

__all__ = [
    "FederationError",
    "FederatedScrape",
    "FleetFederator",
    "federate_snapshots",
    "parse_metric_key",
    "scrape_snapshot",
]

_LABELS = re.compile(r'(\w+)="([^"]*)"')
IDENTITY_KEY = "__identity__"  # the exporter's non-metric identity block


class FederationError(ValueError):
    """Raised when member snapshots cannot merge exactly (e.g. the same
    histogram exported with different bucket bounds)."""


def parse_metric_key(key: str) -> Tuple[str, Dict[str, str]]:
    """``'name{k="v",k2="v2"}'`` → ``('name', {'k': 'v', 'k2': 'v2'})`` —
    the inverse of the snapshot's key format."""
    name, brace, rest = key.partition("{")
    if not brace:
        return key, {}
    return name, dict(_LABELS.findall(rest))


def scrape_snapshot(target: str, timeout_s: float = 5.0) -> Dict[str, Any]:
    """One member's ``/snapshot`` as a dict. ``target`` is a base URL
    (``http://host:port``) or a full ``/snapshot`` URL."""
    url = target if target.endswith("/snapshot") else target.rstrip("/") + "/snapshot"
    with urllib.request.urlopen(url, timeout=timeout_s) as response:  # noqa: S310
        return json.loads(response.read().decode())


def _merge_histogram(merged: Histogram, sample: Mapping[str, Any], name: str) -> None:
    bounds = tuple(float(b) for b in sample.get("buckets", {}))
    if bounds != merged.bounds:
        msg = (
            f"histogram {name!r}: member bounds {list(bounds)} != federated "
            f"bounds {list(merged.bounds)}; exact bucket merge needs one ladder"
        )
        raise FederationError(msg)
    for i, count in enumerate(sample["buckets"].values()):
        merged.counts[i] += int(count)
    merged.counts[-1] += int(sample.get("overflow", 0))
    merged.total += int(sample["count"])
    merged.sum += float(sample["sum"])
    for attr, fold in (("min", min), ("max", max)):
        value = sample.get(attr)
        if value is not None:
            current = getattr(merged, attr)
            setattr(
                merged, attr,
                float(value) if current is None else fold(current, float(value)),
            )
    for exemplar in sample.get("exemplars", ()):
        merged._offer_exemplar(float(exemplar["value"]), str(exemplar["trace_id"]))


def federate_snapshots(
    snapshots: Sequence[Mapping[str, Any]],
    process_labels: Optional[Sequence[str]] = None,
) -> MetricsRegistry:
    """Fold N ``/snapshot`` dicts into one fresh registry (see module doc for
    the per-kind semantics). ``process_labels[i]`` names member ``i``'s gauge
    series; defaults to the member's identity ``process_index``, else ``i``."""
    registry = MetricsRegistry()
    for index, snapshot in enumerate(snapshots):
        identity = snapshot.get(IDENTITY_KEY) or {}
        if process_labels is not None and index < len(process_labels):
            process = str(process_labels[index])
        else:
            process = str(identity.get("process_index", index))
        for key, sample in snapshot.items():
            if key == IDENTITY_KEY or not isinstance(sample, Mapping):
                continue
            name, labels = parse_metric_key(key)
            kind = sample.get("type")
            if kind == "counter":
                registry.inc(name, float(sample["value"]), labels=labels)
            elif kind == "gauge":
                registry.set(
                    name, float(sample["value"]),
                    labels={**labels, "process": process},
                )
            elif kind == "histogram":
                bounds = tuple(float(b) for b in sample.get("buckets", {}))
                if not bounds:
                    continue  # an empty ladder carries nothing to merge
                # same-package privity: build/fetch the merged histogram under
                # the registry lock, then add this member's exact counts
                with registry._lock:
                    merged = registry._get(
                        name, "histogram", labels, lambda b=bounds: Histogram(b)
                    )
                    _merge_histogram(merged, sample, name)
    return registry


class FederatedScrape:
    """One federation pass: the merged registry plus per-member outcome."""

    def __init__(self) -> None:
        self.registry = MetricsRegistry()
        self.members: List[Dict[str, Any]] = []
        self.errors: Dict[str, str] = {}

    @property
    def reachable(self) -> int:
        return len(self.members)


class FleetFederator:
    """Scrape N exporters on a cadence; serve the merged view on one port.

    >>> fed = FleetFederator(["http://127.0.0.1:9100"], port=0)
    >>> scrape = fed.scrape()   # one manual pass, no server needed
    >>> fed.close()
    """

    def __init__(
        self,
        targets: Sequence[str],
        port: int = 0,
        host: str = "127.0.0.1",
        interval_s: float = 5.0,
        timeout_s: float = 5.0,
    ) -> None:
        self.targets = [str(t) for t in targets]
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self._registry = MetricsRegistry()
        self.exporter = MetricsExporter(
            self._registry, port=port, host=host,
            identity={"role": "federator", "members": len(self.targets)},
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def scrape(self) -> FederatedScrape:
        """One federation pass; also swaps the served registry atomically."""
        result = FederatedScrape()
        snapshots: List[Mapping[str, Any]] = []
        for target in self.targets:
            try:
                snapshot = scrape_snapshot(target, timeout_s=self.timeout_s)
            except Exception as exc:  # noqa: BLE001 — a dead member is data
                result.errors[target] = repr(exc)
                continue
            snapshots.append(snapshot)
            identity = dict(snapshot.get(IDENTITY_KEY) or {})
            identity["target"] = target
            result.members.append(identity)
        result.registry = federate_snapshots(snapshots)
        # the federation's own coverage, in the same registry it serves
        result.registry.set("replay_federation_members", float(len(self.targets)))
        result.registry.set("replay_federation_reachable", float(result.reachable))
        for target, error in result.errors.items():
            result.registry.inc(
                "replay_federation_errors_total", labels={"target": target}
            )
            logger.warning("federate: %s unreachable: %s", target, error)
        self._registry = result.registry
        self.exporter.set_registry(result.registry)
        return result

    def start(self) -> "FleetFederator":
        self.exporter.start()
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="fleet-federator", daemon=True
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.scrape()
            except FederationError as exc:
                # a config mismatch must be visible, not fatal to the loop
                logger.warning("federate: scrape failed: %s", exc)
            self._stop.wait(self.interval_s)

    def close(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=self.interval_s + self.timeout_s + 5.0)
        self.exporter.close()

    def __enter__(self) -> "FleetFederator":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.close()


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m replay_tpu.obs.federate",
        description="Scrape N /snapshot exporters into one federated /metrics.",
    )
    parser.add_argument("targets", nargs="+", help="member base URLs (http://host:port)")
    parser.add_argument("--port", type=int, default=9200)
    parser.add_argument("--interval", type=float, default=5.0)
    parser.add_argument(
        "--once", action="store_true",
        help="single scrape: print the merged Prometheus text and exit "
        "(nonzero when no member answered)",
    )
    args = parser.parse_args(argv)

    federator = FleetFederator(args.targets, port=args.port, interval_s=args.interval)
    if args.once:
        scrape = federator.scrape()
        print(scrape.registry.render_prometheus(), end="")
        federator.close()
        return 0 if scrape.reachable else 1
    with federator:
        print(f"federating {len(args.targets)} members on {federator.exporter.url}")
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
