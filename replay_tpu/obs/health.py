"""In-graph model-health diagnostics: layer-wise norms, update ratios,
attention entropy, and an early-warning watcher.

Beyond-parity (SURVEY.md §5): the reference stack leans on Lightning's
``track_grad_norm`` / per-layer logging callbacks to catch silent divergence;
large-batch transformer practice (LAMB, PAPERS.md) treats the layer-wise
update-to-weight ratio as THE stability signal, and the PaLM run report
credits loss-spike recovery to monitoring internals, not loss. Here all of it
is computed *inside* the jitted train step:

* per-parameter-group gradient / parameter / update norms and the
  update-to-param ratio — groups derived from the param tree
  (``embeddings`` / ``block_<i>`` / ``head``);
* activation RMS + absmax per named stage and per-head attention entropy,
  captured via flax ``sow`` on the SASRec/BERT4Rec bodies (the modules sow
  only when the ``intermediates`` collection is mutable, so the
  health-disabled step lowers to byte-identical HLO);
* logits stats (last-position scoring head) and an embedding-row-coverage
  counter (fraction of embedding rows touched by this batch's gradients).

Everything stays on device as scalars/small vectors inside the step's
``metrics`` pytree; the trainer fetches the ``health`` subtree every
``cadence`` steps (one loss-fenced transfer, like ``StepTelemetry``) and
routes it through the ``on_train_step`` / ``on_epoch_end`` events —
TensorBoard sinks render the vector leaves as real histograms, jsonl keeps
the summaries, and ``python -m replay_tpu.obs.report`` renders the
"model health" section. :class:`HealthWatcher` turns the stream into an
early warning: an EWMA blowup of the gradient norm or max update ratio emits
``on_health_warning`` *before* the non-finite sentinel trips, optionally
triggering the RecoveryPolicy rollback path (docs/robustness.md).

Static-shape discipline: enabling health is exactly ONE compiled train-step
variant (the groups and sow sites are resolved at trace time); ``cadence``
is purely host-side, so there are no retraces after step 1.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Tuple

__all__ = [
    "HealthConfig",
    "HealthWatcher",
    "flatten_health",
    "health_metrics",
    "param_group_key",
    "sow_stage_stats",
    "streamed_logits_stats",
]

_BLOCK_RE = re.compile(r"(block_\d+)")
_EPS = 1e-12


# --------------------------------------------------------------------------- #
# configuration
# --------------------------------------------------------------------------- #
@dataclass(frozen=True)
class HealthConfig:
    """What the health-enabled train step computes and how often it is fetched.

    ``cadence`` is the HOST fetch/emit period in steps — the device-side
    computation runs every step (cheap scalar reductions fused into the step)
    so changing the cadence never retraces. ``groups`` controls the per-group
    norm/ratio block; ``activation_stats`` the sowed per-stage RMS/absmax;
    ``attention_entropy`` the per-head entropies; ``logits_stats`` the
    last-position scoring-head stats; ``embedding_coverage`` the fraction of
    embedding rows with non-zero gradient this batch. ``watcher`` attaches an
    early-warning :class:`HealthWatcher` evaluated at every fetch.
    """

    cadence: int = 10
    groups: bool = True
    activation_stats: bool = True
    attention_entropy: bool = True
    logits_stats: bool = True
    embedding_coverage: bool = True
    watcher: Optional["HealthWatcher"] = None

    def __post_init__(self) -> None:
        if self.cadence < 1:
            msg = "cadence must be >= 1 (steps between host fetches)"
            raise ValueError(msg)

    @property
    def capture_intermediates(self) -> bool:
        """Whether the train step must run the forward with the
        ``intermediates`` collection mutable (sow capture)."""
        return self.activation_stats or self.attention_entropy


# --------------------------------------------------------------------------- #
# early warning
# --------------------------------------------------------------------------- #
@dataclass
class HealthWatcher:
    """EWMA blowup detector over the health stream (host-side, O(1) state).

    Tracks an exponentially-weighted moving average of the global gradient
    norm and the max per-group update ratio; a finite observation exceeding
    ``blowup_factor`` × its EWMA (after ``warmup`` clean observations) is a
    warning — fired through ``on_health_warning`` *before* loss/grads go
    non-finite, because norms grow geometrically for several steps before
    they overflow. Warned values are NOT folded into the EWMA (the baseline
    must not chase the blowup), and :meth:`reset` clears the state after a
    RecoveryPolicy rollback (the restored trajectory has pre-blowup norms).

    ``trigger_recovery=True`` asks ``fit`` to treat a warning like a sentinel
    trigger: the RecoveryPolicy (when attached) rolls back immediately
    instead of waiting for ``max_consecutive_bad`` non-finite steps.
    """

    alpha: float = 0.3
    blowup_factor: float = 5.0
    warmup: int = 3
    trigger_recovery: bool = False
    _ewma: Dict[str, float] = field(default_factory=dict, repr=False)
    _seen: int = field(default=0, repr=False)

    def __post_init__(self) -> None:
        if not 0.0 < self.alpha <= 1.0:
            msg = "alpha must be in (0, 1]"
            raise ValueError(msg)
        if self.blowup_factor <= 1.0:
            msg = "blowup_factor must be > 1"
            raise ValueError(msg)
        if self.warmup < 1:
            msg = "warmup must be >= 1"
            raise ValueError(msg)

    @staticmethod
    def _signals(record: Mapping[str, Any]) -> Dict[str, float]:
        signals: Dict[str, float] = {}
        # "grad_norm" proper is the per-GROUP dict; the global norm rides the
        # health record as grad_norm_global (the trainer reuses the sentinel's)
        value = record.get("grad_norm_global", record.get("grad_norm"))
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            signals["grad_norm"] = float(value)
        ratios = record.get("update_ratio")
        if isinstance(ratios, Mapping):
            finite = [
                float(v)
                for v in ratios.values()
                if isinstance(v, (int, float)) and math.isfinite(float(v))
            ]
            if finite:
                signals["update_ratio_max"] = max(finite)
        return signals

    def observe(self, record: Mapping[str, Any]) -> Optional[Dict[str, Any]]:
        """Fold one fetched health record in; a blowup returns the warning
        payload (signal, value, ewma, factor), a clean record returns None.
        Non-finite values are ignored — once loss/grads are NaN the sentinel
        already owns the incident; the watcher's job is the steps before."""
        warning: Optional[Dict[str, Any]] = None
        signals = self._signals(record)
        clean = True
        for name, value in signals.items():
            if not math.isfinite(value):
                continue
            baseline = self._ewma.get(name)
            # each signal's blowup is judged independently: when two blow up
            # on the same fetch, the first becomes THE warning but the second
            # must not slip into its EWMA either (a poisoned baseline would
            # mask that signal's next real warning)
            blown = (
                baseline is not None
                and self._seen >= self.warmup
                and baseline > 0.0
                and value > self.blowup_factor * baseline
            )
            if blown:
                clean = False
                if warning is None:
                    warning = {
                        "signal": name,
                        "value": value,
                        "ewma": baseline,
                        "factor": value / baseline,
                        "blowup_factor": self.blowup_factor,
                    }
                continue  # a blowing-up value must not become the baseline
            self._ewma[name] = (
                value if baseline is None else self.alpha * value + (1 - self.alpha) * baseline
            )
        if signals and clean:
            self._seen += 1
        return warning

    def reset(self) -> None:
        """Forget the baseline (call after a rollback: the restored
        trajectory's norms are pre-blowup)."""
        self._ewma.clear()
        self._seen = 0


# --------------------------------------------------------------------------- #
# in-graph computation (called from inside the jitted train step)
# --------------------------------------------------------------------------- #
def param_group_key(path_str: str) -> str:
    """Parameter-group key for one param-tree path: ``block_<i>`` for encoder
    blocks, ``embeddings`` for any embedding table (feature/positional/mask),
    ``head`` for everything else (norms, aggregator projections, towers)."""
    match = _BLOCK_RE.search(path_str)
    if match:
        return match.group(1)
    if "embed" in path_str.lower():
        return "embeddings"
    return "head"


def _grouped_leaves(tree: Any) -> Dict[str, List[Tuple[str, Any]]]:
    import jax

    groups: Dict[str, List[Tuple[str, Any]]] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        path_str = jax.tree_util.keystr(path)
        groups.setdefault(param_group_key(path_str), []).append((path_str, leaf))
    return groups


def _group_norm(leaves: List[Tuple[str, Any]]):
    import jax.numpy as jnp

    total = sum(jnp.sum(jnp.square(leaf.astype(jnp.float32))) for _, leaf in leaves)
    return jnp.sqrt(total)


def sow_stage_stats(module, name: str, x) -> None:
    """Sow ``<name>_rms`` / ``<name>_absmax`` scalars for one named stage.

    A no-op unless the caller made the ``intermediates`` collection mutable
    (the health-enabled train step); the guard is python-level, so the
    disabled forward lowers to byte-identical HLO.
    """
    if not module.is_mutable_collection("intermediates"):
        return
    import jax.numpy as jnp

    x32 = x.astype(jnp.float32)
    module.sow("intermediates", f"{name}_rms", jnp.sqrt(jnp.mean(jnp.square(x32))))
    module.sow("intermediates", f"{name}_absmax", jnp.max(jnp.abs(x32)))


def _iter_sowed(tree: Any, prefix: str = ""):
    """Flatten a flax ``intermediates`` collection into (path, values) pairs;
    sow stores each site as a tuple (one entry per call — e.g. BERT4Rec's
    ``num_passes_over_block`` repeats), surfaced here as a list."""
    if isinstance(tree, Mapping):
        for key, value in tree.items():
            yield from _iter_sowed(value, f"{prefix}/{key}" if prefix else str(key))
    else:
        values = list(tree) if isinstance(tree, (tuple, list)) else [tree]
        yield prefix, values


def _mean_of(values):
    import jax.numpy as jnp

    if len(values) == 1:
        return values[0]
    return jnp.mean(jnp.stack(values), axis=0)


def health_metrics(
    config: HealthConfig,
    params: Any,
    grads: Any,
    updates: Any,
    intermediates: Optional[Mapping[str, Any]] = None,
    logits: Optional[Any] = None,
) -> Dict[str, Any]:
    """The in-graph ``health`` pytree for one train step (device scalars and
    small vectors only — nothing here forces a host transfer).

    ``params``/``grads``/``updates`` are the step's pre-update parameters,
    raw gradients and optimizer-produced updates; ``intermediates`` is the
    captured flax collection (stage stats + attention entropies sowed by the
    model bodies); ``logits`` is an optional already-computed logits tensor
    for the logits-stats block.
    """
    import jax.numpy as jnp

    health: Dict[str, Any] = {}
    param_groups = _grouped_leaves(params)
    if config.groups:
        grad_groups = _grouped_leaves(grads)
        update_groups = _grouped_leaves(updates)
        health["grad_norm"] = {g: _group_norm(leaves) for g, leaves in grad_groups.items()}
        health["param_norm"] = {g: _group_norm(leaves) for g, leaves in param_groups.items()}
        health["update_norm"] = {
            g: _group_norm(leaves) for g, leaves in update_groups.items()
        }
        health["update_ratio"] = {
            g: health["update_norm"][g] / (health["param_norm"][g] + _EPS)
            for g in health["update_norm"]
            if g in health["param_norm"]
        }
    if config.embedding_coverage:
        # feature VOCAB tables only — the "embedding_<feature>" naming
        # convention the sharding rule table annotates as ("vocab", "embed")
        # (parallel.sharding.logical_axes). Positional/mask tables are
        # touched every batch and would inflate the fraction-of-catalog-rows
        # signal this exists to provide (meaningful under sampled losses).
        def is_vocab_table(path_str: str, leaf) -> bool:
            return "embedding_" in path_str and getattr(leaf, "ndim", 0) == 2

        tables = [
            leaf for path, leaf in param_groups.get("embeddings", []) if is_vocab_table(path, leaf)
        ]
        grad_tables = [
            leaf
            for path, leaf in _grouped_leaves(grads).get("embeddings", [])
            if is_vocab_table(path, leaf)
        ]
        if grad_tables:
            touched = sum(
                jnp.sum(jnp.any(g != 0, axis=tuple(range(1, g.ndim)))) for g in grad_tables
            )
            total_rows = sum(t.shape[0] for t in tables) or 1
            health["embedding_coverage"] = touched.astype(jnp.float32) / float(total_rows)
    if intermediates is not None and (config.activation_stats or config.attention_entropy):
        activations: Dict[str, Dict[str, Any]] = {}
        entropies: Dict[str, Any] = {}
        for path, values in _iter_sowed(intermediates):
            leaf_name = path.rsplit("/", 1)[-1]
            if config.attention_entropy and leaf_name == "attention_entropy":
                match = _BLOCK_RE.search(path)
                entropies[match.group(1) if match else path] = _mean_of(values)
            elif config.activation_stats and leaf_name.endswith(("_rms", "_absmax")):
                stage, _, stat = leaf_name.rpartition("_")
                activations.setdefault(stage, {})[stat] = _mean_of(values)
        if activations:
            health["activations"] = activations
        if entropies:
            health["attention_entropy"] = entropies  # {block: [H] nats}
            health["attention_entropy_mean"] = jnp.mean(
                jnp.concatenate([jnp.ravel(e) for e in entropies.values()])
            )
    if config.logits_stats and logits is not None:
        logits32 = logits.astype(jnp.float32)
        health["logits"] = {
            "mean": jnp.mean(logits32),
            "absmax": jnp.max(jnp.abs(logits32)),
            "std": jnp.std(logits32),
        }
    return health


def streamed_logits_stats(
    hidden: Any, table: Any, chunk: int = 4096
) -> Dict[str, Any]:
    """Last-position logits stats WITHOUT materializing ``[B, num_items]``.

    The memory-wall losses (CEFused/CEFusedTP/SCE/GBCE — ``avoid_full_logits``)
    never build the full logits tensor, and at a million-item catalog the
    health collector must not either: ``[512, 1M]`` f32 is 2 GB for three
    scalars. This sweeps the catalog in ``[B, chunk]`` blocks with a
    ``lax.scan`` (the fused head's tiling discipline applied to diagnostics),
    accumulating sum / sum-of-squares / absmax — the same ``mean``/``std``/
    ``absmax`` the full-logits block reports, up to f32 reassociation across
    chunks. Gradient-free (``stop_gradient``): diagnostics must not change
    the step's backward.
    """
    import jax
    import jax.numpy as jnp

    hidden = jax.lax.stop_gradient(hidden).astype(jnp.float32)  # [B, E]
    table = jax.lax.stop_gradient(table).astype(jnp.float32)  # [I, E]
    num_items, embed = table.shape
    chunk = max(1, min(chunk, num_items))
    pad = -num_items % chunk
    if pad:
        table = jnp.pad(table, ((0, pad), (0, 0)))
    blocks = table.reshape(-1, chunk, embed)
    offsets = jnp.arange(blocks.shape[0]) * chunk
    valid_counts = jnp.clip(num_items - offsets, 0, chunk)

    def fold(carry, block_and_count):
        total, sumsq, absmax = carry
        block, count = block_and_count
        logits = hidden @ block.T  # [B, chunk]
        mask = (jnp.arange(chunk) < count).astype(jnp.float32)
        masked = logits * mask
        total = total + jnp.sum(masked)
        sumsq = sumsq + jnp.sum(masked * masked)
        absmax = jnp.maximum(absmax, jnp.max(jnp.abs(masked)))
        return (total, sumsq, absmax), None

    init = (jnp.float32(0.0), jnp.float32(0.0), jnp.float32(0.0))
    (total, sumsq, absmax), _ = jax.lax.scan(fold, init, (blocks, valid_counts))
    count = jnp.float32(hidden.shape[0] * num_items)
    mean = total / count
    variance = jnp.maximum(sumsq / count - mean * mean, 0.0)
    return {"mean": mean, "absmax": absmax, "std": jnp.sqrt(variance)}


# --------------------------------------------------------------------------- #
# host-side helpers (event payloads / report rendering)
# --------------------------------------------------------------------------- #
def flatten_health(record: Mapping[str, Any], prefix: str = "health") -> Dict[str, Any]:
    """Flatten a fetched health record to ``{tag: scalar-or-vector}`` — the
    TensorBoard routing shape (scalars → ``add_scalar``, vectors →
    ``add_histogram``)."""
    flat: Dict[str, Any] = {}

    def walk(node: Any, tag: str) -> None:
        if isinstance(node, Mapping):
            for key, value in node.items():
                walk(value, f"{tag}/{key}")
        else:
            flat[tag] = node

    walk(record, prefix)
    return flat
