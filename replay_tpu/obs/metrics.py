"""Live metrics plane: a thread-safe registry bridged from the event stream.

Beyond-parity (SURVEY.md §5): the reference's Lightning/TensorBoard story is
per-run, post-hoc logging — nothing in it answers "what is the shed rate RIGHT
NOW on host 3". This module is the in-process half of the live story:

* :class:`MetricsRegistry` — counters (monotone), gauges (last value) and
  fixed-bucket histograms (counts + sum + min/max, with Prometheus-style
  interpolated quantile estimates). Every mutation and every read is taken
  under one registry lock, so a scrape observes a consistent snapshot while
  client/worker threads keep writing.
* :class:`MetricsLogger` — a :class:`~replay_tpu.obs.events.RunLogger` sink
  that derives the registry from the EXISTING event families (``on_train_step``
  / ``on_epoch_end`` / ``on_anomaly`` / health payloads, and the serve family
  ``on_serve_batch`` / ``on_shed`` / ``on_breaker`` / ``on_degrade`` /
  ``on_serve_end``): the Trainer and the ScoringService need no new hooks —
  attaching this sink IS the instrumentation. An optional
  :class:`~replay_tpu.obs.slo.SLOWatchdog` is evaluated at step/batch cadence
  right after the bridge updates, so SLO rules see the freshest values.

The exporter half (``/metrics`` Prometheus text + ``/snapshot`` JSON over a
stdlib HTTP server) lives in :mod:`replay_tpu.obs.exporter`; the declarative
threshold rules in :mod:`replay_tpu.obs.slo`. Metric names are documented in
``docs/observability.md`` (the operator page).

Stdlib-only by design, like :mod:`.report`: importable (and scrape-able) with
no jax involvement.
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Mapping, Optional, Sequence, Tuple

from .events import RunLogger, TrainerEvent

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsLogger",
    "MetricsRegistry",
    "render_prometheus",
]

# Prometheus' default histogram ladder, in seconds — right-sized for step
# times and queue waits in ms-to-minutes territory.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

LabelValue = Tuple[Tuple[str, str], ...]


def _labels_key(labels: Optional[Mapping[str, str]]) -> LabelValue:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _format_value(value: float) -> str:
    """Prometheus text-format number: integers without the trailing ``.0``."""
    if isinstance(value, float) and (math.isinf(value) or math.isnan(value)):
        return "+Inf" if value == math.inf else ("-Inf" if value == -math.inf else "NaN")
    as_float = float(value)
    if as_float == int(as_float) and abs(as_float) < 1e15:
        return str(int(as_float))
    return repr(as_float)


class Counter:
    """Monotone accumulator. Mutate only through the owning registry's lock
    (i.e. via :meth:`MetricsRegistry.inc` or while holding the metric handle
    returned by the registry, which routes through that lock)."""

    kind = "counter"

    def __init__(self) -> None:
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            msg = f"counters are monotone; cannot add {amount}"
            raise ValueError(msg)
        self.value += float(amount)

    def sample(self) -> Dict[str, Any]:
        return {"type": self.kind, "value": self.value}


class Gauge:
    """Last-write-wins scalar."""

    kind = "gauge"

    def __init__(self) -> None:
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)

    def sample(self) -> Dict[str, Any]:
        return {"type": self.kind, "value": self.value}


class Histogram:
    """Fixed upper-bound buckets with Prometheus-style quantile estimates.

    ``buckets`` are the finite upper bounds (``le``); an implicit ``+Inf``
    bucket catches the tail. :meth:`quantile` linearly interpolates inside the
    bucket where the target rank falls (the ``histogram_quantile`` recipe),
    clamped to the observed ``[min, max]`` so small samples on known
    distributions stay honest (tested against numpy percentiles).
    """

    kind = "histogram"

    # slowest-N exemplars kept per histogram: enough to name the offending
    # traces without growing per-request state
    EXEMPLAR_CAPACITY = 8

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS) -> None:
        bounds = sorted(float(b) for b in buckets)
        if not bounds:
            msg = "a histogram needs at least one finite bucket bound"
            raise ValueError(msg)
        if any(math.isinf(b) or math.isnan(b) for b in bounds):
            msg = f"bucket bounds must be finite (got {bounds}); +Inf is implicit"
            raise ValueError(msg)
        self.bounds: Tuple[float, ...] = tuple(bounds)
        self.counts: List[int] = [0] * (len(bounds) + 1)  # + the +Inf bucket
        self.total = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        # bounded slowest-N (value, exemplar) pairs, only populated when a
        # caller passes ``exemplar=`` — a plain histogram pays nothing
        self._exemplars: List[Tuple[float, str]] = []

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        value = float(value)
        if math.isnan(value):
            return  # a NaN observation poisons sum and ranks nothing
        index = len(self.bounds)
        for i, bound in enumerate(self.bounds):
            if value <= bound:
                index = i
                break
        self.counts[index] += 1
        self.total += 1
        self.sum += value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)
        if exemplar is not None:
            self._offer_exemplar(value, str(exemplar))

    def _offer_exemplar(self, value: float, exemplar: str) -> None:
        """Keep the slowest :data:`EXEMPLAR_CAPACITY` (value, exemplar) pairs:
        the tail's trace ids, attached to the distribution that says the tail
        is slow. Mutate under the same lock as :meth:`observe` (the registry's
        or the owning object's)."""
        store = self._exemplars
        if len(store) < self.EXEMPLAR_CAPACITY:
            store.append((value, exemplar))
            store.sort(key=lambda pair: pair[0])
            return
        if value <= store[0][0]:
            return  # faster than the fastest kept exemplar: not tail material
        store[0] = (value, exemplar)
        store.sort(key=lambda pair: pair[0])

    def exemplars(self) -> List[Dict[str, Any]]:
        """Slowest-first ``{value, trace_id}`` records (empty when no
        observation carried an exemplar)."""
        return [
            {"value": value, "trace_id": exemplar}
            for value, exemplar in sorted(self._exemplars, key=lambda p: -p[0])
        ]

    def quantile(self, q: float) -> Optional[float]:
        if not 0.0 <= q <= 1.0:
            msg = f"quantile must be in [0, 1], got {q}"
            raise ValueError(msg)
        if self.total == 0:
            return None
        rank = q * self.total
        cumulative = 0
        for i, bound in enumerate(self.bounds):
            previous = cumulative
            cumulative += self.counts[i]
            if cumulative >= rank:
                lower = self.bounds[i - 1] if i > 0 else min(0.0, bound)
                if self.counts[i]:
                    fraction = (rank - previous) / self.counts[i]
                else:
                    fraction = 0.0
                estimate = lower + (bound - lower) * fraction
                return self._clamp(estimate)
        # the rank lands in the +Inf bucket: the best finite statement is the
        # largest observation
        return self.max

    def _clamp(self, estimate: float) -> float:
        if self.min is not None:
            estimate = max(estimate, self.min)
        if self.max is not None:
            estimate = min(estimate, self.max)
        return estimate

    def mean(self) -> Optional[float]:
        return self.sum / self.total if self.total else None

    def sample(self) -> Dict[str, Any]:
        out = {
            "type": self.kind,
            "count": self.total,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "buckets": {_format_value(b): c for b, c in zip(self.bounds, self.counts)},
            "overflow": self.counts[-1],
            "quantiles": {
                f"p{int(q * 100)}": self.quantile(q) for q in (0.5, 0.9, 0.99)
            },
        }
        if self._exemplars:
            out["exemplars"] = self.exemplars()
        return out


class MetricsRegistry:
    """Named metric instances behind ONE lock.

    A metric is identified by ``(name, labels)``; the first registration fixes
    its type and a later lookup with a different type raises (the one-name-
    one-meaning rule Prometheus enforces at scrape time, enforced here at
    write time instead). All mutators and all readers serialize on the
    registry lock, so a concurrent ``/metrics`` scrape can never observe a
    half-updated histogram or a counter that went backwards.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # name -> (kind, {labels_key: metric})
        self._metrics: Dict[str, Tuple[str, Dict[LabelValue, Any]]] = {}

    def _get(self, name: str, kind: str, labels: Optional[Mapping[str, str]], factory):
        entry = self._metrics.get(name)
        if entry is None:
            entry = (kind, {})
            self._metrics[name] = entry
        elif entry[0] != kind:
            msg = f"metric {name!r} is a {entry[0]}, not a {kind}"
            raise ValueError(msg)
        series = entry[1]
        key = _labels_key(labels)
        metric = series.get(key)
        if metric is None:
            metric = factory()
            series[key] = metric
        return metric

    # -- mutators ----------------------------------------------------------- #
    def inc(self, name: str, amount: float = 1.0, labels: Optional[Mapping[str, str]] = None) -> None:
        with self._lock:
            self._get(name, "counter", labels, Counter).inc(amount)

    def set(self, name: str, value: float, labels: Optional[Mapping[str, str]] = None) -> None:
        with self._lock:
            self._get(name, "gauge", labels, Gauge).set(value)

    def observe(
        self,
        name: str,
        value: float,
        labels: Optional[Mapping[str, str]] = None,
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        exemplar: Optional[str] = None,
    ) -> None:
        with self._lock:
            self._get(name, "histogram", labels, lambda: Histogram(buckets)).observe(
                value, exemplar=exemplar
            )

    # -- readers ------------------------------------------------------------ #
    def value(self, ref: str, labels: Optional[Mapping[str, str]] = None) -> Optional[float]:
        """Scalar read for SLO rules: a counter's total, a gauge's value, or a
        histogram statistic via a ``name:stat`` suffix (``:p50``/``:p99``/...,
        ``:mean``, ``:count``, ``:sum``, ``:max``, ``:min``). ``None`` when the
        metric (or the labeled series) does not exist yet."""
        name, _, stat = ref.partition(":")
        with self._lock:
            entry = self._metrics.get(name)
            if entry is None:
                return None
            metric = entry[1].get(_labels_key(labels))
            if metric is None:
                return None
            if isinstance(metric, Histogram):
                if not stat or stat == "mean":
                    return metric.mean()
                if stat == "count":
                    return float(metric.total)
                if stat == "sum":
                    return metric.sum
                if stat == "max":
                    return metric.max
                if stat == "min":
                    return metric.min
                if stat.startswith("p"):
                    try:
                        q = float(stat[1:]) / 100.0
                    except ValueError:
                        msg = f"unknown histogram stat {stat!r} in {ref!r}"
                        raise ValueError(msg) from None
                    return metric.quantile(q)
                msg = f"unknown histogram stat {stat!r} in {ref!r}"
                raise ValueError(msg)
            if stat:
                msg = f"{name!r} is a {metric.kind}; the :{stat} suffix is for histograms"
                raise ValueError(msg)
            return float(metric.value)

    def snapshot(self) -> Dict[str, Any]:
        """One consistent JSON-able view of every metric (the ``/snapshot``
        endpoint's body)."""
        with self._lock:
            out: Dict[str, Any] = {}
            for name, (_, series) in sorted(self._metrics.items()):
                for key, metric in sorted(series.items()):
                    label_str = (
                        "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}" if key else ""
                    )
                    out[name + label_str] = metric.sample()
            return out

    def render_prometheus(self) -> str:
        """The registry in Prometheus text exposition format (one consistent
        pass under the lock — concurrent writers never tear a line)."""
        with self._lock:
            lines: List[str] = []
            for name, (kind, series) in sorted(self._metrics.items()):
                lines.append(f"# TYPE {name} {kind}")
                for key, metric in sorted(series.items()):
                    label_str = (
                        "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}" if key else ""
                    )
                    if isinstance(metric, Histogram):
                        cumulative = 0
                        for bound, count in zip(metric.bounds, metric.counts):
                            cumulative += count
                            bucket_labels = list(key) + [("le", _format_value(bound))]
                            rendered = ",".join(f'{k}="{v}"' for k, v in bucket_labels)
                            lines.append(f"{name}_bucket{{{rendered}}} {cumulative}")
                        bucket_labels = list(key) + [("le", "+Inf")]
                        rendered = ",".join(f'{k}="{v}"' for k, v in bucket_labels)
                        lines.append(f"{name}_bucket{{{rendered}}} {metric.total}")
                        lines.append(f"{name}_sum{label_str} {_format_value(metric.sum)}")
                        lines.append(f"{name}_count{label_str} {metric.total}")
                    else:
                        lines.append(f"{name}{label_str} {_format_value(metric.value)}")
            return "\n".join(lines) + "\n"


def render_prometheus(registry: MetricsRegistry) -> str:
    """Module-level alias of :meth:`MetricsRegistry.render_prometheus`."""
    return registry.render_prometheus()


# --------------------------------------------------------------------------- #
# the event -> registry bridge
# --------------------------------------------------------------------------- #
_BREAKER_STATES = {"closed": 0.0, "half_open": 1.0, "open": 2.0}
# fleet replica health as a scrapeable ordinal (serve.router.REPLICA_HEALTH)
_REPLICA_HEALTH_STATES = {
    "healthy": 0.0, "degraded": 1.0, "draining": 2.0, "dead": 3.0,
}

# step-time buckets in seconds: sub-ms CPU microbenches up to multi-second
# accelerator steps
STEP_SECONDS_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)
QUEUE_WAIT_MS_BUCKETS: Tuple[float, ...] = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
)
FILL_BUCKETS: Tuple[float, ...] = (0.1, 0.25, 0.5, 0.75, 0.9, 1.0)


def _finite(value: Any) -> Optional[float]:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    value = float(value)
    return value if math.isfinite(value) else None


class MetricsLogger(RunLogger):
    """Bridge the existing event stream into a :class:`MetricsRegistry`.

    Attach it like any other sink (``Trainer.fit(loggers=[...])`` appends it
    automatically when ``metrics_port``/``slo_rules`` are requested; the
    ScoringService routes its ``_emit`` through it): every known event family
    updates the registry, unknown events pass through untouched. After each
    ``on_train_step`` / ``on_serve_batch`` bridge the optional ``watchdog``
    (:class:`~replay_tpu.obs.slo.SLOWatchdog`) is evaluated, so threshold
    rules run at exactly the cadence the issue text calls for — step/batch —
    and never on their own thread.

    Serve QPS is a sliding-window rate (default 10 s) over the rows each
    dispatched batch answered — the live analog of ``bench_serve``'s
    whole-run ``qps``.
    """

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        watchdog: Optional[Any] = None,
        qps_window_seconds: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.registry = registry if registry is not None else MetricsRegistry()
        self.watchdog = watchdog
        self._clock = clock
        self._qps_window = float(qps_window_seconds)
        self._qps_events: Deque[Tuple[float, float]] = collections.deque()
        self._qps_lock = threading.Lock()

    # -- helpers ------------------------------------------------------------ #
    def _gauge(self, name: str, value: Any, labels: Optional[Mapping[str, str]] = None) -> None:
        finite = _finite(value)
        if finite is not None:
            self.registry.set(name, finite, labels=labels)

    def _count(self, name: str, value: Any, labels: Optional[Mapping[str, str]] = None) -> None:
        finite = _finite(value)
        if finite is not None and finite > 0:
            self.registry.inc(name, finite, labels=labels)

    def _serve_qps(self, rows: float) -> None:
        now = self._clock()
        with self._qps_lock:
            self._qps_events.append((now, rows))
            horizon = now - self._qps_window
            while self._qps_events and self._qps_events[0][0] < horizon:
                self._qps_events.popleft()
            window_rows = sum(r for _, r in self._qps_events)
            span = now - self._qps_events[0][0] if len(self._qps_events) > 1 else 0.0
        # a window shorter than one second reads as the window itself, so a
        # burst at startup does not print an absurd rate
        self.registry.set("replay_serve_qps", window_rows / max(span, 1.0))

    def _bridge_goodput(self, goodput: Mapping[str, Any]) -> None:
        fractions = goodput.get("fractions")
        if isinstance(fractions, Mapping):
            for phase, fraction in fractions.items():
                self._gauge(
                    "replay_goodput_fraction", fraction, labels={"phase": str(phase)}
                )
        self._gauge("replay_input_starvation", goodput.get("input_starvation"))

    def _bridge_health(self, health: Mapping[str, Any]) -> None:
        self._gauge("replay_health_grad_norm", health.get("grad_norm_global"))
        ratios = health.get("update_ratio")
        if isinstance(ratios, Mapping):
            finite = [v for v in (_finite(r) for r in ratios.values()) if v is not None]
            if finite:
                self.registry.set("replay_health_max_update_ratio", max(finite))

    # -- the bridge --------------------------------------------------------- #
    def log_event(self, event: TrainerEvent) -> None:  # noqa: C901 — one table
        name, payload = event.event, event.payload
        evaluate = False
        if name == "on_train_step":
            self.registry.inc("replay_train_steps_total")
            self._gauge("replay_train_loss", payload.get("loss"))
            self._gauge("replay_train_lr", payload.get("lr"))
            self._gauge("replay_train_samples_per_sec", payload.get("samples_per_sec"))
            self._gauge("replay_train_steps_per_sec", payload.get("steps_per_sec"))
            # feed efficiency (sequence packing / padding waste): the SLO-able
            # companions to replay_input_starvation
            self._gauge(
                "replay_effective_tokens_per_sec", payload.get("effective_tokens_per_sec")
            )
            self._gauge("replay_padding_fraction", payload.get("padding_fraction"))
            step_seconds = _finite(payload.get("step_seconds"))
            if step_seconds is not None:
                self.registry.observe(
                    "replay_train_step_seconds", step_seconds, buckets=STEP_SECONDS_BUCKETS
                )
            if event.step is not None:
                self._gauge("replay_train_step", event.step)
            health = payload.get("health")
            if isinstance(health, Mapping):
                self._bridge_health(health)
            evaluate = True
        elif name == "on_anomaly":
            self.registry.inc("replay_train_anomalies_total")
            self._gauge("replay_train_bad_steps", payload.get("bad_steps_total"))
            evaluate = True
        elif name == "on_health_warning":
            self.registry.inc("replay_health_warnings_total")
        elif name == "on_recovery":
            self.registry.inc("replay_train_recoveries_total")
        elif name == "on_epoch_end":
            if event.epoch is not None:
                self._gauge("replay_train_epoch", event.epoch)
            record = payload.get("record")
            if isinstance(record, Mapping):
                self._gauge("replay_train_loss_epoch", record.get("train_loss"))
            self._gauge("replay_train_bad_steps", payload.get("bad_steps"))
            goodput = payload.get("goodput")
            if isinstance(goodput, Mapping):
                self._bridge_goodput(goodput)
            health = payload.get("health")
            if isinstance(health, Mapping):
                self._bridge_health(health)
            input_record = payload.get("input")
            if isinstance(input_record, Mapping):
                self._gauge(
                    "replay_effective_tokens_per_sec",
                    input_record.get("effective_tokens_per_sec"),
                )
                self._gauge(
                    "replay_padding_fraction", input_record.get("padding_fraction")
                )
        elif name == "on_fit_start":
            self.registry.set("replay_train_up", 1.0)
        elif name == "on_fit_end":
            self._gauge("replay_train_bad_steps", payload.get("bad_steps"))
            goodput = payload.get("goodput")
            if isinstance(goodput, Mapping):
                self._bridge_goodput(goodput)
            telemetry = payload.get("telemetry")
            if isinstance(telemetry, Mapping):
                self._gauge(
                    "replay_train_samples_per_sec_steady",
                    telemetry.get("samples_per_sec"),
                )
            input_record = payload.get("input")
            if isinstance(input_record, Mapping):
                self._gauge(
                    "replay_effective_tokens_per_sec",
                    input_record.get("effective_tokens_per_sec"),
                )
                self._gauge(
                    "replay_padding_fraction", input_record.get("padding_fraction")
                )
            self.registry.set("replay_train_up", 0.0)
        elif name == "on_serve_start":
            self.registry.set("replay_serve_up", 1.0)
        elif name == "on_serve_batch":
            rows = _finite(payload.get("rows")) or 0.0
            self.registry.inc("replay_serve_batches_total")
            self._count("replay_serve_rows_total", rows)
            self._count("replay_serve_expired_total", payload.get("dropped_expired"))
            self._count("replay_serve_cancelled_total", payload.get("dropped_cancelled"))
            if rows > 0:
                fill = _finite(payload.get("fill"))
                if fill is not None:
                    self.registry.observe(
                        "replay_serve_batch_fill", fill, buckets=FILL_BUCKETS
                    )
                wait_ms = _finite(payload.get("queue_wait_ms_max"))
                if wait_ms is not None:
                    self.registry.observe(
                        "replay_serve_queue_wait_ms", wait_ms, buckets=QUEUE_WAIT_MS_BUCKETS
                    )
            self._serve_qps(rows)
            evaluate = True
        elif name == "on_shed":
            self.registry.inc(
                "replay_serve_shed_total", _finite(payload.get("count")) or 1.0
            )
            lane = payload.get("lane")
            if lane is not None:
                self._gauge(
                    "replay_serve_lane_depth", payload.get("depth"),
                    labels={"lane": str(lane)},
                )
            evaluate = True
        elif name == "on_breaker":
            self.registry.inc("replay_serve_breaker_transitions_total")
            state = _BREAKER_STATES.get(str(payload.get("to")))
            if state is not None:
                self.registry.set("replay_serve_breaker_state", state)
        elif name == "on_degrade":
            self.registry.inc(
                "replay_serve_degraded_total",
                _finite(payload.get("count")) or 1.0,
                labels={"to": str(payload.get("to"))},
            )
        elif name == "on_serve_end":
            for key, metric in (
                ("cache_hit_rate", "replay_serve_cache_hit_rate"),
                ("batch_fill_ratio", "replay_serve_batch_fill_ratio"),
                ("shed_rate", "replay_serve_shed_rate"),
                ("deadline_miss_rate", "replay_serve_deadline_miss_rate"),
                ("error_rate", "replay_serve_error_rate"),
                ("requests", "replay_serve_requests"),
                ("answered", "replay_serve_answered"),
            ):
                self._gauge(metric, payload.get(key))
            self.registry.set("replay_serve_up", 0.0)
        # the fleet family (serve.fleet): per-replica health as a labeled
        # ordinal gauge plus failover/hedge counters — the replay_fleet_*
        # rows docs/observability.md documents
        elif name == "on_fleet_start":
            self.registry.set("replay_fleet_up", 1.0)
            replicas = payload.get("replicas")
            if isinstance(replicas, (list, tuple)):
                self.registry.set("replay_fleet_replicas", float(len(replicas)))
        elif name == "on_replica_health":
            self.registry.inc("replay_fleet_health_transitions_total")
            state = _REPLICA_HEALTH_STATES.get(str(payload.get("to")))
            if state is not None:
                self.registry.set(
                    "replay_fleet_replica_health", state,
                    labels={"replica": str(payload.get("replica"))},
                )
        elif name == "on_failover":
            self.registry.inc("replay_fleet_failovers_total")
        elif name == "on_hedge":
            self.registry.inc("replay_fleet_hedges_total")
        elif name == "on_fleet_end":
            for key, metric in (
                ("requests", "replay_fleet_requests"),
                ("answered", "replay_fleet_answered"),
                ("reroutes", "replay_fleet_reroutes"),
                ("retries", "replay_fleet_retries"),
                ("hedge_wins", "replay_fleet_hedge_wins"),
                ("reroute_rate", "replay_fleet_reroute_rate"),
                ("error_rate", "replay_fleet_error_rate"),
                ("p99_ms", "replay_fleet_p99_ms"),
            ):
                self._gauge(metric, payload.get(key))
            # the fleet's slowest-N latency exemplars, re-observed into a
            # registry histogram so ``/snapshot`` names the offending traces
            exemplars = payload.get("latency_exemplars")
            if isinstance(exemplars, (list, tuple)):
                for record in exemplars:
                    if not isinstance(record, Mapping):
                        continue
                    latency = _finite(record.get("latency_ms"))
                    trace_id = record.get("trace_id")
                    if latency is not None and trace_id:
                        self.registry.observe(
                            "replay_fleet_latency_exemplar_ms",
                            latency,
                            buckets=QUEUE_WAIT_MS_BUCKETS,
                            exemplar=str(trace_id),
                        )
            self.registry.set("replay_fleet_up", 0.0)
        elif name == "on_slo_violation":
            self.registry.inc(
                "replay_slo_violations_total",
                labels={"rule": str(payload.get("rule"))},
            )
        elif name == "on_slo_recovery":
            self.registry.inc(
                "replay_slo_recoveries_total",
                labels={"rule": str(payload.get("rule"))},
            )
        # the promotion family (serve.promote): hot swaps, canary evaluation
        # gauges and the promote/rollback verdicts — replayable from
        # events.jsonl into the same replay_canary_* series the live
        # controller maintains
        elif name == "on_publish":
            self.registry.inc("replay_publish_total")
            if payload.get("recompiled"):
                self.registry.inc("replay_publish_recompiled_total")
        elif name == "on_swap":
            self.registry.inc("replay_swap_total")
            self._gauge("replay_param_generation", payload.get("to_generation"))
        elif name == "on_canary_start":
            self.registry.set("replay_canary_stage", 2.0)
            self._gauge("replay_canary_generation", payload.get("generation"))
        elif name == "on_canary_eval":
            self._gauge("replay_canary_generation", payload.get("generation"))
            self._gauge("replay_canary_error_rate", payload.get("error_rate"))
            self._gauge("replay_canary_clean_evals", payload.get("clean_evals"))
            window = payload.get("window")
            if isinstance(window, Mapping):
                self._gauge("replay_canary_requests", window.get("requests"))
        elif name == "on_promotion":
            self.registry.inc("replay_promotions_total")
            self.registry.set("replay_canary_stage", 3.0)
        elif name == "on_rollback":
            self.registry.inc("replay_rollbacks_total")
            self.registry.set("replay_canary_stage", -1.0)
            self._gauge(
                "replay_param_generation", payload.get("restored_generation")
            )
        # the quality family (obs.quality): per-role windowed model-quality
        # gauges, the online prequential counters and the PSI drift series —
        # evaluate=True arms the drift/canary-quality SLO rules at window
        # cadence, so the drift alarm fires through the normal watchdog
        elif name == "on_quality_window":
            labels = {"role": str(payload.get("role") or "stable")}
            for key, metric in (
                ("coverage", "replay_quality_coverage"),
                ("novelty", "replay_quality_novelty"),
                ("surprisal", "replay_quality_surprisal"),
                ("popularity", "replay_quality_popularity"),
                ("ild", "replay_quality_ild"),
                ("score_entropy", "replay_quality_score_entropy"),
                ("top1_margin", "replay_quality_top1_margin"),
                ("online_hitrate", "replay_quality_online_hitrate"),
                ("online_mrr", "replay_quality_online_mrr"),
                ("online_ndcg", "replay_quality_online_ndcg"),
                ("online_hitrate_cum", "replay_quality_online_hitrate_cum"),
                ("online_mrr_cum", "replay_quality_online_mrr_cum"),
                ("online_ndcg_cum", "replay_quality_online_ndcg_cum"),
                ("joins", "replay_quality_joins"),
                ("requests", "replay_quality_requests"),
            ):
                self._gauge(metric, payload.get(key), labels)
            self.registry.inc("replay_quality_windows_total", labels=labels)
            drift = payload.get("drift")
            if isinstance(drift, Mapping):
                for series, psi in drift.items():
                    if series == "max":
                        self._gauge("replay_drift_psi", psi)
                    else:
                        self._gauge(
                            "replay_drift_psi_series", psi, {"series": str(series)}
                        )
            evaluate = True
        elif name == "on_drift_warning":
            self._count("replay_drift_warnings_total", payload.get("count") or 1.0)
            self._gauge("replay_drift_psi", payload.get("psi_max"))
            evaluate = True
        if evaluate and self.watchdog is not None:
            self.watchdog.evaluate(step=event.step)
