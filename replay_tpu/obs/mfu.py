"""Model-FLOPs-utilization: the XLA cost model and the peak-TFLOPs table.

The single home of the peak dense-bf16 throughput table and the cost-model
FLOPs extraction that ``bench.py`` and ``bench_suite.py`` previously each kept
privately ("Demystifying BERT" argues MFU belongs in every run record, not in
one-off bench scripts — PAPERS.md). Import-light on purpose: drivers import
this before deciding whether jax may be imported at all (the TPU-tunnel health
probe in bench.py).
"""

from __future__ import annotations

from typing import Any, Optional

# peak dense bf16 TFLOP/s per chip, keyed by substring of jax Device.device_kind
PEAK_BF16_TFLOPS = {
    "v5 lite": 197.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v6 lite": 918.0,
    "v6e": 918.0,
    "v4": 275.0,
    "v3": 123.0,
    "v2": 46.0,
}


def peak_tflops(device_kind: str) -> Optional[float]:
    """Peak dense bf16 TFLOP/s for a ``jax.Device.device_kind`` string, or
    None for kinds without a table entry (CPU hosts, unknown chips)."""
    kind = (device_kind or "").lower()
    for key, peak in PEAK_BF16_TFLOPS.items():
        if key in kind:
            return peak
    return None


def cost_analysis(jitted_fn: Any, *args, **kwargs) -> Optional[dict]:
    """XLA's cost analysis of ``jitted_fn`` compiled for ``args`` — normalized
    to one dict across jax versions (older versions return a per-computation
    list), or None when the backend offers no analysis."""
    try:
        analysis = jitted_fn.lower(*args, **kwargs).compile().cost_analysis()
    except Exception:  # best-effort across backends
        return None
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    return analysis if isinstance(analysis, dict) else None


def program_costs(jitted_fn: Any, *args, **kwargs) -> Optional[dict]:
    """Everything the static analyses say about one compiled program, from a
    single ``lower().compile()``: the cost model's ``flops`` / ``bytes
    accessed`` / ``transcendentals``, ``memory_analysis()``'s argument/output/
    temp/code byte sizes, and the optimized HLO text (the input to the
    collective inventory, :mod:`replay_tpu.parallel.introspect`). Fields
    degrade to absence where a backend offers no analysis; returns None only
    when compilation itself is unavailable. ``obs.roofline.analyze_program``
    builds the bound-ness classification on top of this record.
    """
    try:
        compiled = jitted_fn.lower(*args, **kwargs).compile()
    except Exception:  # best-effort across backends
        return None
    return compiled_costs(compiled)


def compiled_costs(compiled: Any) -> Optional[dict]:
    """:func:`program_costs` for an ALREADY-compiled ``jax.stages.Compiled``
    (AOT executables like CompiledInference buckets — no re-lowering)."""
    record: dict = {}
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else None
        if isinstance(analysis, dict):
            record["flops"] = float(analysis.get("flops", 0.0)) or None
            record["bytes_accessed"] = float(analysis.get("bytes accessed", 0.0)) or None
            if "transcendentals" in analysis:
                record["transcendentals"] = float(analysis["transcendentals"])
    except Exception:
        pass
    try:
        memory = compiled.memory_analysis()
        if memory is not None:
            record["memory"] = {
                "argument_bytes": int(getattr(memory, "argument_size_in_bytes", 0)),
                "output_bytes": int(getattr(memory, "output_size_in_bytes", 0)),
                "temp_bytes": int(getattr(memory, "temp_size_in_bytes", 0)),
                "alias_bytes": int(getattr(memory, "alias_size_in_bytes", 0)),
                "generated_code_bytes": int(
                    getattr(memory, "generated_code_size_in_bytes", 0)
                ),
            }
    except Exception:
        pass
    try:
        record["hlo_text"] = compiled.as_text()
    except Exception:
        pass
    return record or None


def flops_per_step(jitted_fn: Any, *args, extra_flops: float = 0.0, **kwargs) -> Optional[float]:
    """Per-call FLOPs of a compiled step from the XLA cost model.

    ``extra_flops`` adds work the cost model cannot see — pallas custom calls
    are opaque to it, so callers add the analytic FLOPs of the kernel they
    fused (e.g. the CEFused head: fwd 2NEI + bwd 2·2NEI).
    """
    analysis = cost_analysis(jitted_fn, *args, **kwargs)
    if not analysis or "flops" not in analysis:
        return None
    flops = float(analysis["flops"])
    if flops <= 0:
        return None
    return flops + float(extra_flops)


def fused_ce_flops(rows: int, embed: int, num_items: int) -> float:
    """Analytic FLOPs of one fused-CE head step (fwd + bwd) for ``rows``
    hidden vectors against a ``num_items`` catalog.

    The pallas kernels are opaque custom calls to the XLA cost model, so the
    head's work must be added back via ``extra_flops`` or every fused-variant
    MFU reads ~0 for exactly the rows where the head dominates: forward
    ``2·N·E·I`` (the logits sweep), backward ``2 × 2·N·E·I`` (the dh and dW
    kernels each rematerialize a logits block and do one matmul). The
    TP-sharded head does the same TOTAL work spread over the mesh — pass the
    global shapes and divide by nothing; ``mfu()`` already normalizes by
    ``device_count``.
    """
    return 6.0 * float(rows) * float(embed) * float(num_items)


def mfu(tflops_per_sec: float, device_kind: str, device_count: int = 1) -> Optional[float]:
    """Achieved ÷ peak TFLOP/s over ``device_count`` chips, or None when the
    chip kind has no peak entry (an MFU against an unknown peak is noise)."""
    peak = peak_tflops(device_kind)
    if not peak or device_count < 1:
        return None
    return float(tflops_per_sec) / (peak * device_count)
