"""Post-mortem reconstruction: what was every process doing when it died?

The forensic half of the black box (:mod:`replay_tpu.obs.blackbox`). A
SIGKILLed fleet leaves four kinds of evidence behind, none of them complete
on its own:

* **flight rings** — each process's last N events, written right up to the
  kill (``read_flight`` tolerates the torn final record);
* **event shards** — the survivors' ``events.jsonl`` / ``events.p<i>.jsonl``
  streams, possibly ending mid-line where a writer died (the tolerant loader
  here skips the torn line and counts it, where :func:`report.load_events`
  would refuse the whole shard);
* **worker meta** — ``workers/rank<i>/meta.json`` written by
  ``launch_workers(run_dir=...)``: the authoritative ``killed_by`` signal,
  returncode and whether the launcher had to reap a wedged survivor;
* **checkpoint sidecars** — ``step_<n>.json`` files naming the last state
  that durably landed.

:func:`build_postmortem` merges them into per-process "last known activity"
timelines: the final flight record, the final event-shard line, the last
checkpoint, the death declaration — and the GAP between the final flight
record and the death declaration, which is exactly the window the run has no
story for. ``python -m replay_tpu.obs.report <run_dir> --postmortem`` renders
it and writes ``postmortem.json`` next to the evidence. Damage is data here:
torn tails and unreadable rings are REPORTED, never raised — a post-mortem
tool that crashes on the corruption it exists to explain is useless.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["build_postmortem", "render_postmortem", "discover_rings"]

_RANK_DIR = re.compile(r"rank(\d+)$")
_SERVER_RING = re.compile(r"flight\.s(\d+)\.ring$")


def discover_rings(run_dir: str) -> List[str]:
    """Every flight ring under a run directory, in stable order: the run
    root's own rings (``flight*.ring``, covering ``flight.ring`` and the
    fleet's ``flight.s<i>.ring``), then each worker rank's."""
    root = glob.escape(run_dir)
    rings = sorted(glob.glob(os.path.join(root, "flight*.ring")))
    rings += sorted(
        glob.glob(os.path.join(root, "workers", "rank*", "flight*.ring"))
    )
    return rings


def _load_events_tolerant(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """Records from an events stream, skipping damaged lines.

    A shard whose writer was SIGKILLed mid-``write`` ends in a torn line;
    the strict :func:`report.load_events` raises on it (correct for a report
    over a healthy run), a post-mortem reads through it. Returns
    ``(records, skipped_line_count)``."""
    records: List[Dict[str, Any]] = []
    skipped = 0
    try:
        with open(path) as fh:
            text = fh.read()
    except OSError:
        return [], 0
    for line in text.splitlines():
        if not line.strip():
            continue
        try:
            record = json.loads(line)
        except ValueError:
            skipped += 1
            continue
        if isinstance(record, dict):
            records.append(record)
        else:
            skipped += 1
    return records, skipped


def _ring_process_key(path: str, flight: Optional[Any]) -> str:
    """A stable per-process label for a ring: the worker rank or fleet
    replica index baked into its path wins; else the recorded
    ``process_index``/role of its ``flight_open`` record; else the writer
    pid."""
    rank = _RANK_DIR.search(os.path.dirname(path))
    if rank:
        return f"rank{rank.group(1)}"
    server = _SERVER_RING.search(os.path.basename(path))
    if server:
        return f"s{server.group(1)}"
    if flight is not None and flight.records:
        first = flight.records[0]
        if first.get("event") == "flight_open":
            if "process_index" in first:
                try:
                    return f"rank{int(first['process_index'])}"
                except (TypeError, ValueError):
                    pass
            if first.get("role"):
                return f"{first['role']}:{flight.writer_pid}"
    pid = flight.writer_pid if flight is not None else "unknown"
    return f"pid{pid}"


def _checkpoint_sidecars(run_dir: str) -> List[Dict[str, Any]]:
    """Every ``step_<n>.json`` checkpoint sidecar under the run dir (root or
    one subdirectory deep — the common ``<run_dir>/ckpt/`` layout), newest
    step last."""
    found = []
    patterns = [
        os.path.join(glob.escape(run_dir), "step_*.json"),
        os.path.join(glob.escape(run_dir), "*", "step_*.json"),
    ]
    for pattern in patterns:
        for path in glob.glob(pattern):
            name = os.path.basename(path)
            match = re.match(r"step_(\d+)\.json$", name)
            if not match:
                continue
            entry: Dict[str, Any] = {
                "path": path,
                "step": int(match.group(1)),
                "saved_unix": os.path.getmtime(path),
            }
            try:
                with open(path) as fh:
                    meta = json.load(fh)
                if isinstance(meta, dict):
                    for key in ("epoch", "mid_epoch", "preempted", "step_in_epoch"):
                        if key in meta:
                            entry[key] = meta[key]
            except (OSError, ValueError):
                entry["unreadable"] = True
            found.append(entry)
    return sorted(found, key=lambda e: e["step"])


def _worker_meta(run_dir: str) -> Dict[str, Dict[str, Any]]:
    """``workers/rank<i>/meta.json`` death declarations, keyed ``rank<i>``.
    ``declared_unix`` is the meta file's mtime — the moment the launcher
    finished the post-exit harvest, the closest thing a SIGKILL leaves to a
    time of death on record."""
    declarations: Dict[str, Dict[str, Any]] = {}
    for path in sorted(
        glob.glob(os.path.join(glob.escape(run_dir), "workers", "rank*", "meta.json"))
    ):
        rank = _RANK_DIR.search(os.path.dirname(path))
        if not rank:
            continue
        try:
            with open(path) as fh:
                meta = json.load(fh)
        except (OSError, ValueError):
            meta = {"unreadable": True}
        meta["declared_unix"] = os.path.getmtime(path)
        meta["path"] = path
        declarations[f"rank{rank.group(1)}"] = meta
    return declarations


def build_postmortem(run_dir: str) -> Dict[str, Any]:
    """Merge a run directory's rings, event shards, worker meta and
    checkpoint sidecars into per-process last-known-activity timelines.

    Never raises for damage inside the evidence (torn rings, torn shard
    lines, unreadable sidecars) — damage is recorded in the result. Raises
    only for a ``run_dir`` that does not exist."""
    from .blackbox import read_flight
    from .report import _collect_event_files

    if not os.path.isdir(run_dir):
        msg = f"{run_dir}: not a run directory"
        raise FileNotFoundError(msg)

    processes: Dict[str, Dict[str, Any]] = {}

    def proc(key: str) -> Dict[str, Any]:
        return processes.setdefault(key, {})

    # -- flight rings ------------------------------------------------------- #
    rings_out: List[Dict[str, Any]] = []
    for path in discover_rings(run_dir):
        try:
            flight = read_flight(path)
        except (OSError, ValueError) as exc:
            rings_out.append(
                {"path": path, "readable": False, "error": repr(exc)}
            )
            continue
        key = _ring_process_key(path, flight)
        entry = {
            "path": path,
            "readable": True,
            "process": key,
            "writer_pid": flight.writer_pid,
            "last_seqno": flight.last_seqno,
            "records_recovered": flight.recovered,
            "torn_tail": flight.torn_tail,
            "dropped": flight.dropped,
        }
        rings_out.append(entry)
        timeline = proc(key)
        timeline["ring"] = path
        timeline["flight_records_recovered"] = flight.recovered
        timeline["torn_tail"] = flight.torn_tail
        if flight.records:
            last = flight.records[-1]
            timeline["last_flight_record"] = {
                k: last.get(k) for k in ("seqno", "t", "event", "step", "epoch")
                if k in last
            }

    # -- event shards (tolerant) -------------------------------------------- #
    shards_out: List[Dict[str, Any]] = []
    try:
        shard_files = _collect_event_files(run_dir)
    except OSError:
        shard_files = []
    for path, index in shard_files:
        records, skipped = _load_events_tolerant(path)
        shards_out.append(
            {
                "path": path,
                "process_index": index,
                "records": len(records),
                "skipped_lines": skipped,
            }
        )
        if not records:
            continue
        key = f"rank{index}"
        timeline = proc(key)
        last = records[-1]
        candidate = {
            k: last.get(k) for k in ("event", "time", "step", "epoch") if k in last
        }
        prior = timeline.get("last_shard_event")
        if prior is None or candidate.get("time", 0) >= prior.get("time", 0):
            timeline["last_shard_event"] = candidate
        if skipped:
            timeline["shard_torn_lines"] = timeline.get("shard_torn_lines", 0) + skipped

    # -- death declarations and checkpoints --------------------------------- #
    for key, meta in _worker_meta(run_dir).items():
        proc(key)["death"] = meta
    checkpoints = _checkpoint_sidecars(run_dir)

    # -- the gap ------------------------------------------------------------ #
    for key, timeline in processes.items():
        death = timeline.get("death")
        last_flight = timeline.get("last_flight_record")
        if death and last_flight and "t" in last_flight:
            timeline["gap_s"] = round(
                max(0.0, death["declared_unix"] - last_flight["t"]), 3
            )
        dead = bool(death) and (
            death.get("returncode") != 0 or death.get("reaped")
        )
        timeline["dead"] = dead or bool(
            death is None and timeline.get("torn_tail")
        )

    return {
        "run_dir": run_dir,
        "processes": processes,
        "rings": rings_out,
        "event_shards": shards_out,
        "checkpoints": checkpoints,
        "torn_tails": sum(1 for r in rings_out if r.get("torn_tail")),
        "unreadable_rings": sum(1 for r in rings_out if not r.get("readable")),
    }


def _fmt_record(record: Optional[Dict[str, Any]]) -> str:
    if not record:
        return "none"
    parts = [str(record.get("event", "?"))]
    if record.get("step") is not None:
        parts.append(f"step={record['step']}")
    if record.get("seqno") is not None:
        parts.append(f"seqno={record['seqno']}")
    when = record.get("t", record.get("time"))
    if when is not None:
        parts.append(f"t={when:.3f}")
    return " ".join(parts)


def render_postmortem(post: Dict[str, Any]) -> str:
    lines = [f"post-mortem: {post['run_dir']}"]
    lines.append(
        f"  rings: {len(post['rings'])} "
        f"(torn tails: {post['torn_tails']}, unreadable: {post['unreadable_rings']})"
    )
    if post["checkpoints"]:
        last_ckpt = post["checkpoints"][-1]
        lines.append(
            f"  last checkpoint: step {last_ckpt['step']}"
            + (" (preempted save)" if last_ckpt.get("preempted") else "")
        )
    for key in sorted(post["processes"]):
        timeline = post["processes"][key]
        status = "DEAD" if timeline.get("dead") else "survived"
        lines.append(f"  {key}: {status}")
        if "flight_records_recovered" in timeline:
            lines.append(
                f"    flight ring: {timeline['flight_records_recovered']} records"
                + (" + torn tail" if timeline.get("torn_tail") else "")
            )
        if timeline.get("last_flight_record"):
            lines.append(
                f"    last flight record: {_fmt_record(timeline['last_flight_record'])}"
            )
        if timeline.get("last_shard_event"):
            lines.append(
                f"    last shard event:   {_fmt_record(timeline['last_shard_event'])}"
            )
        if timeline.get("shard_torn_lines"):
            lines.append(
                f"    shard torn lines:   {timeline['shard_torn_lines']}"
            )
        death = timeline.get("death")
        if death:
            how = (
                f"signal {death['killed_by']}"
                if death.get("killed_by")
                else f"returncode {death.get('returncode')}"
            )
            reaped = " (reaped by launcher)" if death.get("reaped") else ""
            lines.append(f"    death declared:     {how}{reaped}")
        if "gap_s" in timeline:
            lines.append(
                f"    unaccounted gap:    {timeline['gap_s']:.3f}s between final "
                "flight record and death declaration"
            )
    return "\n".join(lines)
