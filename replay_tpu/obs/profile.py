"""Device-time attribution: parse a ``jax.profiler`` capture into per-scope time.

The host-side span layer (:mod:`.trace`) answers *where wall-clock goes between
steps*; this module answers the question "Demystifying BERT" (PAPERS.md) says
an honest utilization number requires: *where does a step's time go on-chip?*
``Trainer.fit(profile_steps=(a, b))`` has captured ``jax.profiler`` traces
since PR 3, and the model/step bodies were labeled with ``jax.named_scope``
(embed / encoder / final_norm / forward / loss / health) in the same PR — but
the scopes were write-only: nothing ever read them back. This module closes
that loop, stdlib-only (gzip + json + re, no jax, no tensorflow):

1. A capture directory holds ``plugins/profile/<run>/<host>.trace.json.gz`` —
   Chrome trace-event JSON whose XLA-op events carry ``args.hlo_op`` /
   ``args.hlo_module`` (:func:`latest_capture`, :func:`load_capture`,
   :func:`device_op_times`).
2. The scope names live in the *compiled program's* HLO metadata
   (``metadata={op_name="jit(train_step)/.../jvp(forward)/dot_general"}``):
   :func:`parse_op_metadata` maps instruction name → op path,
   :func:`scope_of` extracts the deepest named scope from a path (transform
   wrappers like ``jvp(forward)`` / ``transpose(jvp(loss))`` are seen
   through).
3. :func:`attribute_capture` joins the two: per-scope device seconds +
   fractions, per-module totals, and an explicit ``unattributed_seconds``
   (ops outside any named scope — optimizer update, embeddings lookup glue)
   so the breakdown never silently over-claims.

``Trainer.fit`` runs the join automatically when a profile window was
captured and attaches the record as a ``device_time`` payload on
``on_fit_end``; ``obs.report`` renders it as the "device attribution"
section. The same functions work on real-TPU captures (device planes carry
the same ``hlo_op`` args) — the CPU-mesh CI path and the v5e path read one
code.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

__all__ = [
    "NAMED_SCOPES",
    "attribute_capture",
    "device_op_times",
    "hlo_module_name",
    "latest_capture",
    "load_capture",
    "parse_op_metadata",
    "scope_of",
]

# the named scopes the trainer/model bodies emit (nn/train.py `forward`/`loss`/
# `health*`; nn/sequential sasrec `embed`/`encoder`/`final_norm`), in display
# order. Sub-scopes of `forward` come first so the deepest match wins ties in
# rendering; matching itself is positional (rightmost segment in the op path).
NAMED_SCOPES = (
    "embed",
    "encoder",
    "final_norm",
    "health_logits",
    "health",
    "forward",
    "loss",
)

# `%dot.5 = f32[...] dot(...), metadata={op_name="jit(f)/jvp(forward)/dot" ...}`
_METADATA_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s.*?metadata=\{[^}]*"
    r"op_name=\"(?P<op_name>[^\"]+)\"",
    re.MULTILINE,
)

# the dump header: `HloModule jit_train_step, is_scheduled=true, ...` — the
# same name the profiler emits as the events' `hlo_module` arg
_MODULE_RE = re.compile(r"^HloModule\s+([\w.\-]+)", re.MULTILINE)


def hlo_module_name(hlo_text: str) -> Optional[str]:
    """The module name of an ``as_text()`` dump, or None without a header."""
    match = _MODULE_RE.search(hlo_text)
    return match.group(1) if match else None


def latest_capture(profile_dir: str) -> Optional[str]:
    """Newest ``*.trace.json.gz`` under ``profile_dir`` (the layout
    ``jax.profiler.start_trace`` writes: ``plugins/profile/<run>/<host>.
    trace.json.gz``), or None when nothing was captured."""
    pattern = os.path.join(profile_dir, "plugins", "profile", "*", "*.trace.json.gz")
    captures = sorted(glob.glob(pattern), key=os.path.getmtime)
    return captures[-1] if captures else None


def load_capture(path: str) -> List[Dict[str, Any]]:
    """The ``traceEvents`` list of a (gzipped) Chrome trace-event capture."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as fh:
        payload = json.load(fh)
    events = payload.get("traceEvents") if isinstance(payload, Mapping) else payload
    if not isinstance(events, list):
        msg = f"{path}: no traceEvents list"
        raise ValueError(msg)
    return [e for e in events if isinstance(e, Mapping)]


def device_op_times(events: Iterable[Mapping[str, Any]]) -> Dict[Tuple[str, str], float]:
    """Aggregate XLA-op execution events into ``{(module, op): seconds}``.

    An XLA-op event is a complete event (``ph == "X"``) whose args carry
    ``hlo_op`` — true on CPU host planes and TPU device planes alike; host
    python/runtime spans carry no ``hlo_op`` and are excluded, so the totals
    are device(-executor) time, not wall clock.
    """
    totals: Dict[Tuple[str, str], float] = {}
    for event in events:
        if event.get("ph") != "X":
            continue
        args = event.get("args")
        if not isinstance(args, Mapping) or "hlo_op" not in args:
            continue
        duration = event.get("dur", 0)
        if not isinstance(duration, (int, float)) or duration < 0:
            continue
        key = (str(args.get("hlo_module", "")), str(args["hlo_op"]))
        totals[key] = totals.get(key, 0.0) + float(duration) / 1e6
    return totals


def parse_op_metadata(hlo_text: str) -> Dict[str, str]:
    """``{instruction_name: op_name_path}`` from an HLO ``as_text()`` dump.

    Fusions report the fusion root's ``op_name`` — the same name the profiler
    emits as ``hlo_op`` for the fused kernel, so the join stays 1:1.
    """
    mapping: Dict[str, str] = {}
    for match in _METADATA_RE.finditer(hlo_text):
        mapping.setdefault(match.group("name"), match.group("op_name"))
    return mapping


def scope_of(op_path: str, scopes: Sequence[str] = NAMED_SCOPES) -> Optional[str]:
    """The deepest named scope appearing in an op metadata path.

    Scope labels survive jax transforms wrapped — ``jvp(forward)``,
    ``transpose(jvp(loss))``, ``remat(encoder)`` — so a scope matches as a
    whole path segment OR inside transform parentheses. The rightmost
    (deepest) match wins: an op under ``.../forward/embed/...`` belongs to
    ``embed``, not ``forward``.
    """
    best: Tuple[int, Optional[str]] = (-1, None)
    for scope in scopes:
        pattern = re.compile(r"(?:^|/|\()" + re.escape(scope) + r"(?:\)|/|$)")
        last = None
        for match in pattern.finditer(op_path):
            last = match
        if last is not None and last.start() > best[0]:
            best = (last.start(), scope)
    return best[1]


def attribute_capture(
    profile_dir: str,
    hlo_texts: Optional[Mapping[str, str] | str] = None,
    scopes: Sequence[str] = NAMED_SCOPES,
) -> Dict[str, Any]:
    """Join a profiler capture with compiled-program metadata → per-scope time.

    :param profile_dir: the directory handed to ``jax.profiler.start_trace``
        (``Trainer.fit``'s ``profile_dir``).
    :param hlo_texts: compiled HLO ``as_text()`` dumps to resolve scopes
        against — a single string or ``{label: text}`` (one per compiled
        program that ran in the window). None attributes nothing (every op
        lands in ``unattributed_seconds``) but still totals device time.
    :returns: ``{"capture", "total_device_seconds", "modules": {module:
        seconds}, "scopes": {scope: {"seconds", "fraction"}},
        "attributed_seconds", "unattributed_seconds"}`` — fractions are of
        total device time, and attributed + unattributed == total by
        construction.
    :raises FileNotFoundError: no capture under ``profile_dir``.
    """
    capture = latest_capture(profile_dir)
    if capture is None:
        msg = f"{profile_dir}: no jax.profiler capture (plugins/profile/*/*.trace.json.gz)"
        raise FileNotFoundError(msg)
    op_times = device_op_times(load_capture(capture))

    texts: Dict[str, str]
    if hlo_texts is None:
        texts = {}
    elif isinstance(hlo_texts, str):
        texts = {"program": hlo_texts}
    else:
        texts = dict(hlo_texts)
    # instruction names are MODULE-LOCAL counters (`fusion.3` exists in both
    # the step and the scan program with different op paths), so the join is
    # keyed per module — the flat map is only the fallback for events whose
    # hlo_module has no parsed header (renamed/suffixed SPMD modules)
    paths_by_module: Dict[str, Dict[str, str]] = {}
    op_paths: Dict[str, str] = {}
    for text in texts.values():
        parsed = parse_op_metadata(text)
        module_name = hlo_module_name(text)
        if module_name is not None:
            paths_by_module.setdefault(module_name, {}).update(parsed)
        for name, op_path in parsed.items():
            op_paths.setdefault(name, op_path)

    total = 0.0
    modules: Dict[str, float] = {}
    scope_seconds: Dict[str, float] = {}
    attributed = 0.0
    for (module, op), seconds in op_times.items():
        total += seconds
        modules[module] = modules.get(module, 0.0) + seconds
        op_path = paths_by_module[module].get(op) if module in paths_by_module else op_paths.get(op)
        scope = scope_of(op_path, scopes) if op_path else None
        if scope is not None:
            scope_seconds[scope] = scope_seconds.get(scope, 0.0) + seconds
            attributed += seconds
    ordered = {
        scope: {
            "seconds": scope_seconds[scope],
            "fraction": scope_seconds[scope] / total if total > 0 else 0.0,
        }
        for scope in (*scopes, *sorted(set(scope_seconds) - set(scopes)))
        if scope in scope_seconds
    }
    return {
        "capture": capture,
        "total_device_seconds": total,
        "modules": modules,
        "scopes": ordered,
        "attributed_seconds": attributed,
        "unattributed_seconds": max(total - attributed, 0.0),
    }
