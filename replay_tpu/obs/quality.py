"""Online recommendation-quality telemetry: the model-quality half of the
metrics plane (docs/observability.md "The quality plane").

The system half of the observability stack answers "is the service up, fast,
and alive"; this module answers "is the model still good" — continuously, from
live traffic, with no new hot-path hooks (the PR-10 pattern: a sink consuming
what the serving path already produces). The reference stack has no online
analogue at all: its evaluation stops at `replay/metrics/` offline batteries
(SURVEY §2.6) — here those exact formulas run on every served slate.

Three parts:

* **Response-side telemetry** — :class:`QualityMonitor` consumes served top-k
  cuts (``ScoreResponse`` via :func:`replay_tpu.serve.request.top_k_cut`) into
  sliding-window, per-``role``-labeled gauges: catalog coverage, mean
  popularity (popularity bias), novelty and surprisal (the
  ``metrics/beyond_accuracy`` pure functions against a pure-JSON
  :class:`PopularityDescriptor` snapshot), popularity-decile intra-list
  diversity, and score-distribution stats (normalized softmax entropy, top-1
  margin). Stable vs canary quality is comparable in ONE scrape.
* **Streaming prequential eval** — a bounded per-user store of the last served
  slate is joined against incoming ``new_items`` interactions (every window
  advance is a delayed ground-truth label the serving path carries for free)
  producing online hitrate@k / MRR@k / NDCG@k — windowed AND cumulative — with
  exactly the ``metrics/ranking.py`` per-user formulas (reconciled to float
  tolerance in tests/serve/test_quality_service.py).
* **Drift detection + gating** — :class:`DriftDetector` computes reference-vs-
  window PSI (population stability index) over the score / popularity /
  interactions (incoming-label popularity) / coverage series; the bridge exposes everything as ``replay_quality_*`` and
  ``replay_drift_*`` registry series (exporter + federation ride along), and
  the :data:`QUALITY_SLOS` cookbook rules make the ``SLOWatchdog`` fire the
  drift alarm exactly once per excursion and the ``PromotionController`` roll
  back a canary whose QUALITY (not just error rate) degrades
  (:func:`canary_quality_rules`).

Events: ``on_quality_window`` (one per role per emission window, INFO render)
and ``on_drift_warning`` (throttled like ``on_shed``) ride the normal RunLogger
sink fan-out via the owning service's ``_emit`` — ``obs.metrics.MetricsLogger``
bridges them into the registry.
"""

from __future__ import annotations

import json
import math
import threading
from collections import OrderedDict, deque
from typing import Any, Callable, Deque, Dict, List, Mapping, Optional, Sequence, Tuple

from .slo import SLORule


def _beyond_accuracy():
    """Lazy seam to the offline per-slate math: ``replay_tpu.metrics``'s
    package import pulls jax (builder), and ``replay_tpu.obs`` must stay
    jax-free at import time — resolved on first observation instead."""
    from ..metrics import beyond_accuracy

    return beyond_accuracy

__all__ = [
    "DriftDetector",
    "PopularityDescriptor",
    "QUALITY_SLOS",
    "QualityMonitor",
    "canary_quality_rules",
    "population_stability_index",
    "prequential_scores",
]


# ---------------------------------------------------------------------------
# offline↔online shared math
# ---------------------------------------------------------------------------


def prequential_scores(
    slate: Sequence[int], ground_truth: Sequence[int], k: int
) -> Tuple[float, float, float]:
    """``(hit@k, rr@k, ndcg@k)`` of ONE served slate against ONE delayed
    ground-truth list — exactly the per-user formulas of
    ``metrics/ranking.py`` (:class:`~replay_tpu.metrics.HitRate` /
    :class:`~replay_tpu.metrics.MRR` / :class:`~replay_tpu.metrics.NDCG`):
    hit = any relevant item in the top-k window; rr = 1/(first-hit rank);
    NDCG discounts 1/log2(rank+2) with IDCG truncating the RAW ground-truth
    length at k. Served slates are duplicate-free, so the occurrence/first-
    occurrence hit matrices coincide.
    """
    head = list(slate[:k])
    gt_list = list(ground_truth)
    if not head or not gt_list:
        return 0.0, 0.0, 0.0
    gt_set = set(gt_list)
    hit = 0.0
    rr = 0.0
    dcg = 0.0
    for rank, item in enumerate(head):
        if item in gt_set:
            hit = 1.0
            if rr == 0.0:
                rr = 1.0 / (rank + 1.0)
            dcg += 1.0 / math.log2(rank + 2.0)
    idcg = sum(1.0 / math.log2(i + 2.0) for i in range(min(len(gt_list), k)))
    ndcg = dcg / idcg if idcg > 0.0 else 0.0
    return hit, rr, ndcg


def population_stability_index(
    reference: Sequence[float],
    current: Sequence[float],
    edges: Sequence[float],
    epsilon: float = 1e-4,
) -> float:
    """PSI of ``current`` vs ``reference`` over shared bin ``edges``:
    ``sum((p_i - q_i) * ln(p_i / q_i))`` with epsilon-smoothed, renormalized
    bin fractions. Values outside the edge range clamp into the boundary bins
    (a shifted distribution lands in the tails instead of vanishing).
    Rule of thumb: < 0.1 stable · 0.1–0.25 moderate shift · > 0.25 major shift.
    """
    if not reference or not current or len(edges) < 2:
        return 0.0

    def _fractions(values: Sequence[float]) -> List[float]:
        counts = [0.0] * (len(edges) - 1)
        for value in values:
            lo, hi = 0, len(edges) - 2
            if value <= edges[0]:
                bin_index = 0
            elif value >= edges[-1]:
                bin_index = hi
            else:
                bin_index = lo
                while bin_index < hi and value > edges[bin_index + 1]:
                    bin_index += 1
            counts[bin_index] += 1.0
        total = sum(counts) + epsilon * len(counts)
        return [(c + epsilon) / total for c in counts]

    p = _fractions(reference)
    q = _fractions(current)
    return float(sum((pi - qi) * math.log(pi / qi) for pi, qi in zip(p, q)))


# ---------------------------------------------------------------------------
# the popularity snapshot (pure JSON)
# ---------------------------------------------------------------------------


class PopularityDescriptor:
    """A pure-JSON catalog-popularity snapshot the online monitor scores
    slates against — the frozen offline side of the offline↔online seam.

    Built once from a training/interactions log (``from_train``), it carries
    per-item distinct-consumer counts and derives exactly the
    ``metrics/beyond_accuracy`` quantities: surprisal weights
    (``log2(n_users/consumers)/log2(n_users)``, unseen → 1.0), popularity
    fractions (consumers / n_users) and popularity deciles (0 = head,
    9 = tail) used by the decile intra-list-diversity proxy. ``to_json`` /
    ``from_json`` round-trip it as a deployable artifact next to the model.
    """

    def __init__(self, consumers: Mapping[int, int], n_users: int, num_items: Optional[int] = None) -> None:
        self.consumers: Dict[int, int] = {int(i): int(c) for i, c in consumers.items() if int(c) > 0}
        self.n_users = int(n_users)
        self.num_items = int(num_items) if num_items is not None else (max(self.consumers) + 1 if self.consumers else 0)
        self.train_items = set(self.consumers)
        log_n = math.log2(self.n_users) if self.n_users > 1 else 1.0
        self._weights: Dict[int, float] = {
            item: math.log2(self.n_users / count) / log_n if self.n_users > 1 else 1.0
            for item, count in self.consumers.items()
        }
        denom = float(self.n_users) if self.n_users > 0 else 1.0
        self._popularity: Dict[int, float] = {item: count / denom for item, count in self.consumers.items()}
        # decile by popularity rank (count desc, item asc tiebreak): 0 = head
        ranked = sorted(self.consumers, key=lambda item: (-self.consumers[item], item))
        n = len(ranked)
        self._decile: Dict[int, int] = {item: min(9, (10 * rank) // n) for rank, item in enumerate(ranked)} if n else {}

    @classmethod
    def from_train(cls, train: Mapping[Any, Sequence[int]], num_items: Optional[int] = None) -> "PopularityDescriptor":
        """From a ``{user: [item, ...]}`` interactions log (the same input the
        offline Surprisal/Novelty/Coverage metrics take)."""
        consumers: Dict[int, set] = {}
        for user, items in train.items():
            for item in items:
                consumers.setdefault(int(item), set()).add(user)
        return cls({item: len(users) for item, users in consumers.items()}, len(train), num_items)

    def surprisal_weight(self, item: int) -> float:
        return self._weights.get(int(item), 1.0)

    def popularity(self, item: int) -> float:
        return self._popularity.get(int(item), 0.0)

    def decile(self, item: int) -> int:
        """Popularity decile (0 = most popular tenth, 9 = tail); unseen items
        are tail by definition."""
        return self._decile.get(int(item), 9)

    def to_json(self) -> str:
        return json.dumps(
            {
                "n_users": self.n_users,
                "num_items": self.num_items,
                "consumers": {str(i): c for i, c in sorted(self.consumers.items())},
            }
        )

    @classmethod
    def from_json(cls, payload: str) -> "PopularityDescriptor":
        record = json.loads(payload)
        return cls(
            {int(i): int(c) for i, c in record["consumers"].items()},
            int(record["n_users"]),
            int(record["num_items"]),
        )


# ---------------------------------------------------------------------------
# drift
# ---------------------------------------------------------------------------


class DriftDetector:
    """Reference-vs-window PSI over one scalar series.

    The first ``reference_size`` observations freeze the reference histogram
    (uniform bins over the observed range, widened by a relative margin so
    near-boundary values don't flap bins); later observations fill a sliding
    window, and :meth:`psi` compares window vs reference once at least
    ``min_window`` samples arrived. Tuning levers: more ``bins`` = finer but
    noisier; a larger ``reference_size`` = a steadier baseline; a larger
    ``window`` = slower but surer detection.
    """

    def __init__(
        self,
        bins: int = 10,
        reference_size: int = 256,
        window: int = 256,
        min_window: int = 32,
        epsilon: float = 1e-4,
    ) -> None:
        if bins < 2:
            msg = "DriftDetector needs at least 2 bins"
            raise ValueError(msg)
        self.bins = int(bins)
        self.reference_size = int(reference_size)
        self.min_window = int(min_window)
        self.epsilon = float(epsilon)
        self._reference: List[float] = []
        self._edges: Optional[List[float]] = None
        self._window: Deque[float] = deque(maxlen=int(window))

    @property
    def ready(self) -> bool:
        return self._edges is not None and len(self._window) >= self.min_window

    def observe(self, value: float) -> None:
        value = float(value)
        if not math.isfinite(value):
            return
        if self._edges is None:
            self._reference.append(value)
            if len(self._reference) >= self.reference_size:
                self._freeze()
            return
        self._window.append(value)

    def _freeze(self) -> None:
        lo, hi = min(self._reference), max(self._reference)
        span = (hi - lo) or max(abs(lo), 1.0) * 1e-6
        lo -= 0.05 * span
        hi += 0.05 * span
        step = (hi - lo) / self.bins
        self._edges = [lo + i * step for i in range(self.bins + 1)]

    def psi(self) -> Optional[float]:
        if not self.ready:
            return None
        return population_stability_index(
            self._reference, list(self._window), self._edges, self.epsilon
        )

    def state(self) -> Dict[str, Any]:
        return {
            "reference": len(self._reference),
            "window": len(self._window),
            "ready": self.ready,
            "psi": self.psi(),
        }


# ---------------------------------------------------------------------------
# SLO cookbook
# ---------------------------------------------------------------------------

#: Quality-plane SLO cookbook (docs/observability.md "The quality plane").
#: ``drift_psi`` is the preference-shift alarm — the watchdog's transition-fire
#: semantics make it fire EXACTLY once per sustained excursion; the ``canary_*``
#: rules gate the candidate traffic slice and belong in
#: ``PromotionController(rules=...)`` (see :func:`canary_quality_rules` for
#: tuned thresholds). Thresholds are cookbook defaults — tune per catalog.
QUALITY_SLOS: Tuple[SLORule, ...] = (
    SLORule("replay_drift_psi", ">", 0.25, for_steps=2, name="drift_psi"),
    SLORule(
        "replay_quality_online_hitrate",
        "<",
        0.01,
        for_steps=2,
        labels={"role": "candidate"},
        name="canary_online_hitrate",
    ),
    SLORule(
        "replay_quality_coverage",
        "<",
        0.005,
        for_steps=2,
        labels={"role": "candidate"},
        name="canary_coverage",
    ),
)


def canary_quality_rules(
    min_online_hitrate: Optional[float] = None,
    min_coverage: Optional[float] = None,
    min_novelty: Optional[float] = None,
    max_popularity: Optional[float] = None,
    for_steps: int = 2,
) -> Tuple[SLORule, ...]:
    """Quality rules over the CANDIDATE traffic slice, for
    ``PromotionController(rules=...)`` — a canary whose served quality drops
    below these floors (or whose popularity bias exceeds the cap) is rolled
    back even when its error rate and latency look healthy. Only the passed
    thresholds produce rules.
    """
    labels = {"role": "candidate"}
    rules: List[SLORule] = []
    if min_online_hitrate is not None:
        rules.append(
            SLORule(
                "replay_quality_online_hitrate", "<", float(min_online_hitrate),
                for_steps=for_steps, labels=labels, name="canary_online_hitrate",
            )
        )
    if min_coverage is not None:
        rules.append(
            SLORule(
                "replay_quality_coverage", "<", float(min_coverage),
                for_steps=for_steps, labels=labels, name="canary_coverage",
            )
        )
    if min_novelty is not None:
        rules.append(
            SLORule(
                "replay_quality_novelty", "<", float(min_novelty),
                for_steps=for_steps, labels=labels, name="canary_novelty",
            )
        )
    if max_popularity is not None:
        rules.append(
            SLORule(
                "replay_quality_popularity", ">", float(max_popularity),
                for_steps=for_steps, labels=labels, name="canary_popularity_bias",
            )
        )
    return tuple(rules)


# ---------------------------------------------------------------------------
# the monitor
# ---------------------------------------------------------------------------


class _RoleWindow:
    """Sliding-window quality state for one traffic role (stable/candidate)."""

    def __init__(self, window: int) -> None:
        self.requests = 0
        self.slates: Deque[Tuple[int, ...]] = deque(maxlen=window)
        self.novelty: Deque[float] = deque(maxlen=window)
        self.surprisal: Deque[float] = deque(maxlen=window)
        self.popularity: Deque[float] = deque(maxlen=window)
        self.ild: Deque[float] = deque(maxlen=window)
        self.entropy: Deque[float] = deque(maxlen=window)
        self.margin: Deque[float] = deque(maxlen=window)
        self.prequential: Deque[Tuple[float, float, float]] = deque(maxlen=window)
        self.joins = 0
        self.hit_sum = 0.0
        self.rr_sum = 0.0
        self.ndcg_sum = 0.0


def _mean(values) -> Optional[float]:
    values = list(values)
    if not values:
        return None
    return sum(values) / len(values)


class QualityMonitor:
    """Consumes served responses into windowed quality gauges, prequential
    online accuracy and drift detection — one ``observe()`` per response,
    thread-safe, never raising into the serving path (the owning service
    detaches a failing monitor).

    Attach via ``ScoringService(quality=QualityMonitor(descriptor))``; the
    service binds ``emit``/``emit_throttled`` so ``on_quality_window`` /
    ``on_drift_warning`` ride its sink fan-out (and, through
    ``MetricsLogger``, its registry/exporter/federation).
    """

    def __init__(
        self,
        descriptor: PopularityDescriptor,
        k: int = 10,
        window: int = 256,
        max_users: int = 10_000,
        emit_every: int = 64,
        drift_bins: int = 10,
        drift_reference: int = 256,
        drift_window: int = 256,
        drift_min_window: int = 32,
        drift_threshold: float = 0.25,
        max_seen_per_user: int = 512,
        emit: Optional[Callable[[str, Dict[str, Any]], None]] = None,
        emit_throttled: Optional[Callable[[str, str, Dict[str, Any]], None]] = None,
    ) -> None:
        self.descriptor = descriptor
        self.k = int(k)
        self.window = int(window)
        self.max_users = int(max_users)
        self.emit_every = max(int(emit_every), 1)
        self.drift_threshold = float(drift_threshold)
        self.max_seen_per_user = int(max_seen_per_user)
        self._emit = emit
        self._emit_throttled = emit_throttled
        self._lock = threading.Lock()
        self._roles: "OrderedDict[str, _RoleWindow]" = OrderedDict()
        # bounded per-user state: last served slate (+ the role that served
        # it) for the prequential join, and the seen-items set for novelty
        self._last_slate: "OrderedDict[Any, Tuple[Tuple[int, ...], str]]" = OrderedDict()
        self._seen: "OrderedDict[Any, OrderedDict]" = OrderedDict()
        self._observed = 0
        self._since_emit = 0
        self._drift = {
            "score": DriftDetector(drift_bins, drift_reference, drift_window, drift_min_window),
            "popularity": DriftDetector(drift_bins, drift_reference, drift_window, drift_min_window),
            "interactions": DriftDetector(drift_bins, drift_reference, drift_window, drift_min_window),
            "coverage": DriftDetector(
                drift_bins,
                max(drift_reference // self.emit_every, 4),
                max(drift_window // self.emit_every, 4),
                max(drift_min_window // self.emit_every, 2),
            ),
        }
        self._drift_alarmed = False
        self.drift_warnings = 0
        self.windows_emitted = 0

    def bind(
        self,
        emit: Callable[[str, Dict[str, Any]], None],
        emit_throttled: Optional[Callable[[str, str, Dict[str, Any]], None]] = None,
    ) -> None:
        """Wire the monitor into an event fan-out (the owning service's
        ``_emit`` / ``_emit_throttled``)."""
        self._emit = emit
        self._emit_throttled = emit_throttled

    # -- per-response ingestion -------------------------------------------

    def observe(self, response, request=None) -> None:
        """Ingest one served response (and, when the paired request carried
        ``new_items``, the delayed ground-truth labels of that user's LAST
        served slate — the prequential join happens BEFORE the new slate is
        stored)."""
        from ..serve.request import top_k_cut  # lazy: obs must not import serve at module load

        item_ids, scores = top_k_cut(response, self.k)
        slate = tuple(int(i) for i in item_ids.tolist())
        score_list = [float(s) for s in scores.tolist()]
        role = str(getattr(response, "role", "stable") or "stable")
        user = response.user_id
        ground_truth = tuple(int(i) for i in (getattr(request, "new_items", None) or ()))
        history = tuple(int(i) for i in (getattr(request, "history", None) or ()))
        with self._lock:
            self._ingest(user, slate, score_list, role, ground_truth, history)
            emit_now = self._since_emit >= self.emit_every
            if emit_now:
                self._since_emit = 0
        if emit_now:
            self._emit_windows()

    def _ingest(
        self,
        user,
        slate: Tuple[int, ...],
        scores: List[float],
        role: str,
        ground_truth: Tuple[int, ...],
        history: Tuple[int, ...] = (),
    ) -> None:
        self._observed += 1
        self._since_emit += 1
        window = self._roles.get(role)
        if window is None:
            window = self._roles[role] = _RoleWindow(self.window)
        window.requests += 1

        # (1) prequential join: the user's PREVIOUS slate vs the labels that
        # just arrived — credited to the role that served that slate
        if ground_truth and user in self._last_slate:
            previous, previous_role = self._last_slate[user]
            prev_window = self._roles.get(previous_role)
            if prev_window is None:
                prev_window = self._roles[previous_role] = _RoleWindow(self.window)
            hit, rr, ndcg = prequential_scores(previous, ground_truth, self.k)
            prev_window.prequential.append((hit, rr, ndcg))
            prev_window.joins += 1
            prev_window.hit_sum += hit
            prev_window.rr_sum += rr
            prev_window.ndcg_sum += ndcg

        # (2) the user's seen set absorbs the interactions that PRECEDE this
        # slate (history refresh + the incremental tail), bounded LRU-style
        seen = self._seen.get(user)
        interactions = history + ground_truth
        if interactions:
            if seen is None:
                seen = self._seen[user] = OrderedDict()
            for item in interactions:
                seen[item] = None
                seen.move_to_end(item)
            while len(seen) > self.max_seen_per_user:
                seen.popitem(last=False)
            self._seen.move_to_end(user)
            while len(self._seen) > self.max_users:
                self._seen.popitem(last=False)

        # (3) response-side telemetry on the new slate
        pure = _beyond_accuracy()
        window.novelty.append(pure.novelty_of_slate(slate, seen or (), self.k))
        window.surprisal.append(
            pure.surprisal_of_slate(slate, self.descriptor._weights, self.k) if slate else 0.0
        )
        popularity = _mean(self.descriptor.popularity(item) for item in slate)
        window.popularity.append(popularity if popularity is not None else 0.0)
        window.ild.append(self._decile_ild(slate))
        entropy, margin = self._score_stats(scores)
        window.entropy.append(entropy)
        window.margin.append(margin)
        if slate:
            window.slates.append(slate)

        # (4) drift series (role-blind: the fleet-level preference signal).
        # "interactions" watches what users DO (incoming-label popularity —
        # the direct preference-shift signal); "score"/"popularity" watch what
        # the model serves in response; "coverage" is fed at emission cadence.
        if scores:
            self._drift["score"].observe(scores[0])
        if popularity is not None:
            self._drift["popularity"].observe(popularity)
        if ground_truth:
            label_popularity = _mean(
                self.descriptor.popularity(item) for item in ground_truth
            )
            if label_popularity is not None:
                self._drift["interactions"].observe(label_popularity)

        # (5) the last served slate, for the NEXT prequential join
        if slate:
            self._last_slate[user] = (slate, role)
            self._last_slate.move_to_end(user)
            while len(self._last_slate) > self.max_users:
                self._last_slate.popitem(last=False)

    def _decile_ild(self, slate: Tuple[int, ...]) -> float:
        """Popularity-decile intra-list diversity: the fraction of slate pairs
        whose items sit in DIFFERENT popularity deciles — 0.0 for a slate all
        drawn from one decile (pure head or pure tail), 1.0 for maximal
        head/tail mixing. A features-free ILD proxy the descriptor can score."""
        if len(slate) < 2:
            return 0.0
        deciles = [self.descriptor.decile(item) for item in slate]
        pairs = 0
        different = 0
        for i in range(len(deciles)):
            for j in range(i + 1, len(deciles)):
                pairs += 1
                if deciles[i] != deciles[j]:
                    different += 1
        return different / pairs

    @staticmethod
    def _score_stats(scores: List[float]) -> Tuple[float, float]:
        """(normalized softmax entropy, top-1 margin) of the slate's scores —
        a collapsing score distribution (entropy → 0, margin exploding) is an
        early model-rot signal independent of labels."""
        finite = [s for s in scores if math.isfinite(s)]
        if len(finite) < 2:
            return 0.0, 0.0
        top = max(finite)
        exps = [math.exp(s - top) for s in finite]
        total = sum(exps)
        probs = [e / total for e in exps]
        entropy = -sum(p * math.log(p) for p in probs if p > 0.0)
        entropy /= math.log(len(probs))
        ordered = sorted(finite, reverse=True)
        return entropy, ordered[0] - ordered[1]

    # -- window emission ---------------------------------------------------

    def _window_payload(self, role: str, window: _RoleWindow, drift: Dict[str, Any]) -> Dict[str, Any]:
        recommended = set()
        for slate in window.slates:
            recommended.update(slate)
        coverage = _beyond_accuracy().coverage_of(recommended, self.descriptor.train_items)
        preq = list(window.prequential)
        payload: Dict[str, Any] = {
            "role": role,
            "k": self.k,
            "requests": window.requests,
            "window": len(window.slates),
            "coverage": coverage,
            "novelty": _mean(window.novelty),
            "surprisal": _mean(window.surprisal),
            "popularity": _mean(window.popularity),
            "ild": _mean(window.ild),
            "score_entropy": _mean(window.entropy),
            "top1_margin": _mean(window.margin),
            "joins": window.joins,
            "online_hitrate": _mean(h for h, _, _ in preq),
            "online_mrr": _mean(rr for _, rr, _ in preq),
            "online_ndcg": _mean(n for _, _, n in preq),
            "online_hitrate_cum": window.hit_sum / window.joins if window.joins else None,
            "online_mrr_cum": window.rr_sum / window.joins if window.joins else None,
            "online_ndcg_cum": window.ndcg_sum / window.joins if window.joins else None,
            "drift": drift,
        }
        return payload

    #: the series the alarm (and the ``max`` entry, i.e. the
    #: ``replay_drift_psi`` gauge the SLO rules watch) is computed over:
    #: per-observation distributions with enough samples for PSI to mean
    #: something. "coverage" is fed ONE aggregate value per emitted window,
    #: so its PSI is dominated by traffic-mix and small-sample noise —
    #: surfaced in the series dict (and the ``replay_drift_psi_series``
    #: gauge) for dashboards, never part of the alarmed max.
    ALARMED_SERIES = ("score", "popularity", "interactions")

    def _drift_state(self) -> Dict[str, Any]:
        psis = {}
        for series, detector in self._drift.items():
            psi = detector.psi()
            if psi is not None:
                psis[series] = psi
        drift: Dict[str, Any] = dict(psis)
        alarmed = [psis[s] for s in self.ALARMED_SERIES if s in psis]
        if alarmed:
            drift["max"] = max(alarmed)
        return drift

    def _emit_windows(self) -> None:
        """Emit one ``on_quality_window`` per role (gauges land via the
        MetricsLogger bridge) and the drift alarm when PSI crosses the
        threshold — latched, so one excursion warns exactly once."""
        with self._lock:
            # coverage drift observes the stable window's coverage series at
            # emission cadence (coverage is a window property, not per-slate)
            stable = self._roles.get("stable")
            if stable is not None and stable.slates:
                recommended = set()
                for slate in stable.slates:
                    recommended.update(slate)
                self._drift["coverage"].observe(
                    _beyond_accuracy().coverage_of(recommended, self.descriptor.train_items)
                )
            drift = self._drift_state()
            payloads = [
                self._window_payload(role, window, drift)
                for role, window in self._roles.items()
                if window.requests
            ]
            self.windows_emitted += len(payloads)
            warn_payload = None
            psi_max = drift.get("max")
            if psi_max is not None and psi_max > self.drift_threshold:
                if not self._drift_alarmed:
                    self._drift_alarmed = True
                    self.drift_warnings += 1
                    series = max(
                        (s for s in self.ALARMED_SERIES if s in drift),
                        key=lambda s: drift[s],
                    )
                    warn_payload = {
                        "series": series,
                        "psi": drift[series],
                        "psi_max": psi_max,
                        "threshold": self.drift_threshold,
                    }
            elif psi_max is not None and psi_max <= 0.5 * self.drift_threshold:
                # hysteresis: re-arm at HALF the threshold, so a series
                # jittering at the boundary warns once per excursion rather
                # than once per wiggle
                self._drift_alarmed = False
        if self._emit is not None:
            for payload in payloads:
                self._emit("on_quality_window", payload)
            if warn_payload is not None:
                if self._emit_throttled is not None:
                    self._emit_throttled("drift", "on_drift_warning", warn_payload)
                else:
                    self._emit("on_drift_warning", warn_payload)

    def flush(self) -> None:
        """Emit the final (possibly partial) windows — called by the owning
        service at close so short runs still land their gauges."""
        with self._lock:
            pending = self._since_emit
            self._since_emit = 0
        if pending:
            self._emit_windows()

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Pure-JSON state for ``stats()`` / bench records / tests."""
        with self._lock:
            drift = self._drift_state()
            roles = {
                role: {
                    key: value
                    for key, value in self._window_payload(role, window, drift).items()
                    if key not in ("role", "drift")
                }
                for role, window in self._roles.items()
            }
            return {
                "observed": self._observed,
                "k": self.k,
                "windows_emitted": self.windows_emitted,
                "drift_warnings": self.drift_warnings,
                "drift": drift,
                "drift_state": {s: d.state() for s, d in self._drift.items()},
                "roles": roles,
            }
