"""Run-report CLI: summarize a run's ``events.jsonl`` (+ ``trace.json``).

::

    python -m replay_tpu.obs.report <run_dir | events.jsonl | BENCH.json>
    python -m replay_tpu.obs.report runs/exp2 --compare runs/exp1 --threshold 0.1

Turns the telemetry artifacts every trainer/bench/dry run leaves behind into
the one-page answer "Demystifying BERT" (PAPERS.md) says a profile must
become: throughput, MFU, the goodput breakdown (where wall-clock went between
steps), the DEVICE-time attribution (obs.profile: per-``named_scope`` on-chip
time from a profiled fit) and per-program roofline records (obs.roofline:
memory- vs compute-bound, predicted ceiling, HBM footprint, collective
bytes), retraces, bad/recovered steps, the model-health record
(obs.health: per-group norms/update ratios, activation stats, attention
entropy, early warnings), and the serving summary (replay_tpu.serve /
bench_serve.py: QPS, latency percentiles, batch fill, cache hit rate, plus
the resilience rates — shed / deadline-miss / error — with degraded-traffic
counts by ladder rung and breaker state; gated on QPS drops, p99 growth, and
lower-better ``serve_error_rate`` / ``serve_deadline_miss_rate`` rises —
``serve_shed_rate`` gates only when BOTH runs ran the overload phase).
A run directory is read as ONE merged stream: size-rotation backups
(``events.jsonl.N``, oldest first) and multi-host per-process shards
(``events.p<i>.jsonl``) fold together, each record keeping (or inheriting
from its filename) a ``process_index`` stamp — from which the report computes
per-host step time, the cross-host skew and the straggler index
(max/median per-host step time). ``on_slo_violation`` events (obs.slo) are
counted and gated lower-better. ``--compare`` diffs two runs —
either run may be a run directory, a raw ``events.jsonl``, or a single-record
bench JSON (``BENCH_*.json`` / ``BENCH_TPU_SIDECAR.json``) — and exits
non-zero when the candidate regresses beyond ``--threshold`` (relative):
throughput/MFU drops, new retraces, ``peak_memory_bytes`` growth beyond
``--memory-threshold``, ``compile_seconds`` growth beyond
``--compile-threshold``, and per-bench-row throughput (rows with an ``error``
field — by-design OOM evidence — are skipped, not tripped on), so CI can
gate on it. A fleet run's merged ``trace.json`` (serve.fleet distributed
tracing) additionally yields the "tail attribution" section — p50/p99 of
traced requests decomposed into per-hop fractions summing to 1.0 — plus the
slowest-request exemplar trace ids; ``--compare`` gates a hop's p99 SHARE
growing by more than 10 points even when p99 itself is flat.

Import-light by design (stdlib only): the CLI must run in seconds with no
jax/device involvement, and a malformed artifact must fail loudly (non-zero
exit) rather than render a partial report — CI uses that as the "our own
artifacts still parse" check.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from .trace import GOODPUT_SPANS, SERVE_GOODPUT_SPANS, tail_attribution

__all__ = [
    "compare_runs",
    "load_events",
    "load_trace_events",
    "main",
    "render",
    "straggler_summary",
    "summarize_run",
]


def _finite(value: Any) -> Optional[float]:
    """``value`` as a finite float, else None (events.jsonl writes NaN as null)."""
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    value = float(value)
    return value if math.isfinite(value) else None


# --------------------------------------------------------------------------- #
# loading
# --------------------------------------------------------------------------- #
def _with_rotations(path: str) -> List[str]:
    """``path`` preceded by its size-rotation backups, oldest first
    (``events.jsonl.3``, ``.2``, ``.1``, then ``events.jsonl`` — the order
    :class:`~replay_tpu.obs.events.JsonlLogger(max_bytes=...)` wrote them)."""
    import glob

    rotated = []
    for backup in glob.glob(glob.escape(path) + ".*"):
        suffix = backup[len(path) + 1 :]
        if suffix.isdigit():
            rotated.append((int(suffix), backup))
    ordered = [backup for _, backup in sorted(rotated, reverse=True)]
    if os.path.exists(path):
        ordered.append(path)
    return ordered


def _collect_event_files(run_dir: str) -> List[Tuple[str, int]]:
    """Every events shard of a run directory as ``(path, process_index)``,
    in merge order: process 0's rotation chain (``events.jsonl``), then each
    non-zero process's ``events.p<i>.jsonl`` chain — the multi-host layout
    where every process writes its own shard."""
    import glob
    import re

    files: List[Tuple[str, int]] = [
        (path, 0) for path in _with_rotations(os.path.join(run_dir, "events.jsonl"))
    ]
    shard_name = re.compile(r"events\.p(\d+)\.jsonl$")
    shards = []
    for path in glob.glob(os.path.join(glob.escape(run_dir), "events.p*.jsonl")):
        match = shard_name.search(os.path.basename(path))
        if match:
            shards.append((int(match.group(1)), path))
    for index, path in sorted(shards):
        files.extend((chained, index) for chained in _with_rotations(path))
    return files


def _resolve(path: str) -> Tuple[List[Tuple[str, int]], Optional[str]]:
    """([(events path, process index), ...], trace path or None) for a run
    directory or a bare file."""
    if os.path.isdir(path):
        files = _collect_event_files(path)
        if not files:
            msg = f"{path}: no events.jsonl in run directory"
            raise FileNotFoundError(msg)
        trace = os.path.join(path, "trace.json")
        return files, trace if os.path.exists(trace) else None
    return [(path, 0)], None


def load_events(path: str) -> List[Dict[str, Any]]:
    """Records from an ``events.jsonl`` stream or a single-record JSON file."""
    with open(path) as fh:
        text = fh.read()
    records: List[Any]
    try:
        payload = json.loads(text)
        records = [payload] if isinstance(payload, Mapping) else list(payload)
    except ValueError:
        records = []
        for lineno, line in enumerate(text.splitlines(), 1):
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except ValueError as exc:
                msg = f"{path}:{lineno}: invalid JSON ({exc})"
                raise ValueError(msg) from exc
    if not records:
        msg = f"{path}: no records"
        raise ValueError(msg)
    for i, record in enumerate(records):
        if not isinstance(record, Mapping):
            msg = f"{path}: record {i} is not a JSON object"
            raise ValueError(msg)
    return [dict(r) for r in records]


def load_trace_events(path: str) -> List[Dict[str, Any]]:
    """The validated raw ``traceEvents`` list of a Chrome trace-event JSON.

    The validation IS the contract check CI leans on: every event must carry
    ``name``/``ph``/``ts`` and a non-negative duration. Tail attribution needs
    the per-event ``trace_id`` args the name-level aggregation of
    :func:`load_trace` folds away, so the raw list is its own loader.
    """
    with open(path) as fh:
        payload = json.load(fh)
    events = payload.get("traceEvents") if isinstance(payload, Mapping) else payload
    if not isinstance(events, list):
        msg = f"{path}: no traceEvents list"
        raise ValueError(msg)
    for i, event in enumerate(events):
        if not isinstance(event, Mapping) or not all(
            key in event for key in ("name", "ph", "ts")
        ):
            msg = f"{path}: traceEvents[{i}] missing name/ph/ts"
            raise ValueError(msg)
        duration = event.get("dur", 0)
        if not isinstance(duration, (int, float)) or duration < 0:
            msg = f"{path}: traceEvents[{i}] has a negative or non-numeric dur"
            raise ValueError(msg)
    return [dict(e) for e in events]


def _aggregate_trace(events: Sequence[Mapping[str, Any]]) -> Dict[str, Dict[str, float]]:
    spans: Dict[str, Dict[str, float]] = {}
    for event in events:
        if event.get("ph") == "M":
            # metadata (the merged fleet trace's process_name track labels):
            # not a timed span, excluded from the name-level aggregation
            continue
        entry = spans.setdefault(str(event["name"]), {"count": 0, "seconds": 0.0})
        entry["count"] += 1
        entry["seconds"] += float(event.get("dur", 0)) / 1e6
    return spans


def load_trace(path: str) -> Dict[str, Dict[str, float]]:
    """Validate Chrome trace-event JSON and aggregate ``{name: {count, seconds}}``."""
    return _aggregate_trace(load_trace_events(path))


# --------------------------------------------------------------------------- #
# summarizing
# --------------------------------------------------------------------------- #
def straggler_summary(per_process: Mapping[Any, float]) -> Dict[str, Any]:
    """Cross-host step-time spread from per-process mean step seconds.

    ``straggler_index`` is max/median (1.0 = perfectly balanced; 2.0 = the
    slowest host takes twice the typical step), ``skew`` is the relative
    spread ``(max - min) / median``; ``straggler`` names the slowest process.
    Pure host math — also used by ``dryrun_multichip`` to stamp its record.
    """
    if not per_process:
        msg = "straggler_summary needs at least one process"
        raise ValueError(msg)
    values = sorted(float(v) for v in per_process.values())
    n = len(values)
    median = values[n // 2] if n % 2 else 0.5 * (values[n // 2 - 1] + values[n // 2])
    worst = max(per_process, key=lambda key: float(per_process[key]))
    return {
        "max_step_seconds": values[-1],
        "median_step_seconds": median,
        "straggler": str(worst),
        "straggler_index": values[-1] / median if median > 0 else None,
        "skew": (values[-1] - values[0]) / median if median > 0 else None,
    }


def summarize_run(path: str) -> Dict[str, Any]:
    event_files, trace_path = _resolve(path)
    events: List[Dict[str, Any]] = []
    for events_path, process_index in event_files:
        for record in load_events(events_path):
            if process_index and "process_index" not in record:
                # a shard written before per-record stamping existed: the
                # filename still carries the process identity
                record["process_index"] = process_index
            events.append(record)
    raw_trace = load_trace_events(trace_path) if trace_path else None
    summary = summarize_events(events, source=path)
    if raw_trace is not None:
        trace = _aggregate_trace(raw_trace)
        summary["trace"] = trace
        # total h2d time ACROSS threads: in a chunked run the device feed
        # places chunks on a feeder thread, so most of this never shows up in
        # the fit thread's goodput fractions — the delta IS the overlap win
        if "h2d" in trace:
            summary["h2d_seconds"] = float(trace["h2d"]["seconds"])
        # tail attribution (fleet traces): decompose the slow tail of traced
        # requests into per-hop fractions — None for training traces, whose
        # spans carry no request roots
        attribution = tail_attribution(raw_trace)
        if attribution is not None:
            summary["tail_attribution"] = attribution
    return summary


def summarize_events(
    events: Sequence[Mapping[str, Any]], source: str = ""
) -> Dict[str, Any]:
    """Fold an event stream into one flat summary record (pure host math)."""
    steps = [e for e in events if e.get("event") == "on_train_step"]
    epoch_ends = [e for e in events if e.get("event") == "on_epoch_end"]
    fit_ends = [e for e in events if e.get("event") == "on_fit_end"]
    # bench sidecars are RAW records (log_record, no "event" key); the guard
    # keeps on_slo_violation — whose payload also carries metric+value — out
    bench = [e for e in events if "metric" in e and "value" in e and "event" not in e]
    bench_rows = [e for e in events if e.get("event") == "bench_row"]
    dryruns = [e for e in events if e.get("event") == "dryrun_multichip"]
    serve_ends = [e for e in events if e.get("event") == "on_serve_end"]
    serve_batches = [e for e in events if e.get("event") == "on_serve_batch"]

    summary: Dict[str, Any] = {
        "source": source,
        "events": len(events),
        "kind": (
            "fit"
            if fit_ends or steps
            else (
                "bench"
                if bench or bench_rows
                else (
                    "serve"
                    if serve_ends or serve_batches
                    else ("dryrun" if dryruns else "events")
                )
            )
        ),
        "train_steps": len(steps),
        "epochs": len(epoch_ends),
        "anomalies": sum(1 for e in events if e.get("event") == "on_anomaly"),
        "recoveries": sum(1 for e in events if e.get("event") == "on_recovery"),
        "preemptions": sum(1 for e in events if e.get("event") == "on_preemption"),
        "health_warnings": sum(
            1 for e in events if e.get("event") == "on_health_warning"
        ),
        # the SLO watchdog's transition events (obs.slo): violations are the
        # lower-better --compare gate; recoveries separate transient spikes
        # from breaches that were still open when the run ended
        "slo_violations": sum(
            1 for e in events if e.get("event") == "on_slo_violation"
        ),
        "slo_recoveries": sum(
            1 for e in events if e.get("event") == "on_slo_recovery"
        ),
        # the promotion loop (serve.promote): swaps are routine, rollbacks are
        # the lower-better --compare gate (a healthy continual run rolls
        # nothing back, so ANY candidate rollback against a clean baseline
        # fires — same zero-baseline rule as slo_violations)
        "swaps": sum(1 for e in events if e.get("event") == "on_swap"),
        "promotions": sum(1 for e in events if e.get("event") == "on_promotion"),
        "rollbacks": sum(1 for e in events if e.get("event") == "on_rollback"),
    }
    summary["slo_rules_fired"] = sorted(
        {
            str(e.get("rule"))
            for e in events
            if e.get("event") == "on_slo_violation" and e.get("rule") is not None
        }
    )
    summary["backend"] = next(
        (e["backend"] for e in events if isinstance(e.get("backend"), str)), None
    )

    # the promotion record (canary lifecycle): publishes, the canary verdict
    # trail and the last generation each pointer landed on
    promotion_events = [
        e for e in events
        if e.get("event") in (
            "on_publish", "on_swap", "on_canary_start", "on_canary_eval",
            "on_promotion", "on_rollback",
        )
    ]
    if promotion_events:
        swaps = [e for e in promotion_events if e.get("event") == "on_swap"]
        evals = [e for e in promotion_events if e.get("event") == "on_canary_eval"]
        promotion: Dict[str, Any] = {
            "publishes": sum(
                1 for e in promotion_events if e.get("event") == "on_publish"
            ),
            "recompiled_publishes": sum(
                1 for e in promotion_events
                if e.get("event") == "on_publish" and e.get("recompiled")
            ),
            "canaries": sum(
                1 for e in promotion_events if e.get("event") == "on_canary_start"
            ),
            "canary_evals": len(evals),
            "swaps": summary["swaps"],
            "promotions": summary["promotions"],
            "rollbacks": summary["rollbacks"],
        }
        if swaps:
            promotion["last_generation"] = swaps[-1].get("to_generation")
        if evals:
            last_eval = evals[-1]
            promotion["last_canary_error_rate"] = _finite(
                last_eval.get("error_rate")
            )
            promotion["last_clean_evals"] = last_eval.get("clean_evals")
        rollbacks = [
            e for e in promotion_events if e.get("event") == "on_rollback"
        ]
        if rollbacks:
            promotion["rollback_rules"] = sorted(
                {
                    str(rule)
                    for e in rollbacks
                    for rule in (e.get("rules") or [])
                }
            )
        summary["promotion"] = promotion
    else:
        summary["promotion"] = None

    fit_end = fit_ends[-1] if fit_ends else {}
    telemetry = fit_end.get("telemetry") or {}
    # on-chip observability (obs.profile / obs.roofline): the per-named-scope
    # device-time attribution and per-program roofline records a profiled fit
    # attaches to its terminal event
    summary["device_time"] = (
        dict(fit_end["device_time"]) if isinstance(fit_end.get("device_time"), Mapping) else None
    )
    summary["roofline"] = (
        dict(fit_end["roofline"]) if isinstance(fit_end.get("roofline"), Mapping) else None
    )
    summary["bad_steps"] = fit_end.get("bad_steps")
    if summary["bad_steps"] is None:
        # crashed/killed runs have no on_fit_end: the epoch-end rollup is the
        # next best sentinel evidence
        summary["bad_steps"] = next(
            (e["bad_steps"] for e in reversed(epoch_ends) if "bad_steps" in e), None
        )
    summary["last_grad_norm"] = next(
        (
            value
            for e in reversed(epoch_ends)
            for value in [_finite(e.get("grad_norm"))]
            if value is not None
        ),
        None,
    )

    # the latest model-health record (obs.health): rides on_train_step /
    # on_epoch_end events from health-enabled fits, and dryrun_multichip records
    summary["health"] = next(
        (dict(e["health"]) for e in reversed(list(events)) if isinstance(e.get("health"), Mapping)),
        None,
    )

    # throughput: steady-state fit telemetry > bench headline > step-event mean
    throughput = _finite(telemetry.get("samples_per_sec"))
    steps_per_sec = _finite(telemetry.get("steps_per_sec"))
    throughput_source = "telemetry" if throughput is not None else None
    if throughput is None and bench:
        record = bench[-1]
        if "samples_per_sec" in str(record.get("metric", "")):
            throughput = _finite(record.get("value"))
            throughput_source = "bench"
    if throughput is None and steps:
        rates = [r for r in (_finite(e.get("samples_per_sec")) for e in steps) if r]
        if rates:
            throughput = sum(rates) / len(rates)
            throughput_source = "steps"
    if steps_per_sec is None and steps:
        rates = [r for r in (_finite(e.get("steps_per_sec")) for e in steps) if r]
        if rates:
            steps_per_sec = sum(rates) / len(rates)
    summary["samples_per_sec"] = throughput
    summary["steps_per_sec"] = steps_per_sec
    summary["throughput_source"] = throughput_source

    # multi-host view: per-process mean step time from the merged shards'
    # stamped step events, folded into the skew/straggler record. Only
    # rendered when any step event carries a process stamp — single-process
    # runs stay byte-identical.
    by_process: Dict[int, List[float]] = {}
    stamped = False
    for e in steps:
        if "process_index" in e:
            stamped = True
        step_seconds = _finite(e.get("step_seconds"))
        if step_seconds is not None:
            by_process.setdefault(int(e.get("process_index") or 0), []).append(
                step_seconds
            )
    if stamped and by_process:
        per_process = {
            pid: sum(values) / len(values) for pid, values in by_process.items()
        }
        summary["processes"] = {
            "count": len(per_process),
            "step_seconds": {
                str(pid): value for pid, value in sorted(per_process.items())
            },
            **straggler_summary(per_process),
        }
    else:
        summary["processes"] = None

    losses = [
        value
        for e in epoch_ends
        for value in [_finite((e.get("record") or {}).get("train_loss"))]
        if value is not None
    ]
    summary["final_train_loss"] = losses[-1] if losses else None

    # compile report: {fn: {traces, compile_seconds}} — retraces beyond the
    # one sanctioned trace per jitted fn are the static-shapes leak signal
    compile_report: Mapping[str, Any] = fit_end.get("compile") or {}
    if not compile_report and dryruns:
        compile_report = dryruns[-1].get("compile") or {}
    if compile_report:
        summary["compile"] = dict(compile_report)
        summary["retraces"] = sum(
            max(int(entry.get("traces", 0)) - 1, 0)
            for entry in compile_report.values()
            if isinstance(entry, Mapping)
        )
        summary["compile_seconds"] = sum(
            float(entry.get("compile_seconds", 0.0))
            for entry in compile_report.values()
            if isinstance(entry, Mapping)
        )
    elif bench and _finite(bench[-1].get("compile_seconds")) is not None:
        summary["compile_seconds"] = float(bench[-1]["compile_seconds"])

    # the latest goodput breakdown (epoch-end beats fit-end: fit-end wall
    # includes startup/compile, epoch windows are the steady state)
    goodput = None
    for event in reversed(list(events)):
        if event.get("event") == "on_epoch_end" and isinstance(event.get("goodput"), Mapping):
            goodput = dict(event["goodput"])
            break
    if goodput is None:
        for event in reversed(list(events)):
            if isinstance(event.get("goodput"), Mapping):
                goodput = dict(event["goodput"])
                break
    summary["goodput"] = goodput

    # feed efficiency (docs/performance.md "Feeding the beast"): real vs grid
    # tokens + effective-tokens/s, attached by fit to epoch/fit-end events
    summary["input"] = next(
        (
            dict(e["input"])
            for e in reversed(list(events))
            if isinstance(e.get("input"), Mapping)
        ),
        None,
    )

    if bench:
        record = bench[-1]
        summary["bench"] = {
            key: record.get(key)
            for key in (
                "metric", "value", "unit", "vs_baseline", "backend", "mfu",
                "tflops_per_sec", "step_ms", "dispatch_step_ms", "scan_k",
                "compile_seconds", "device_kind", "source", "stale",
                # the end-to-end Trainer.fit(scan_chunk=...) loop and its
                # variant flags (a fit measured with a different chunk size or
                # the feed disabled must not read as the baseline)
                "fit_samples_per_sec", "fit_step_ms", "fit_scan_chunk",
                "fit_device_feed", "dispatch_gap_closed",
            )
            if key in record
        }
        summary["mfu"] = _finite(record.get("mfu"))
        # first-class so --compare can gate on the PRODUCTION loop's
        # throughput, not only the hand-rolled microbench number
        summary["fit_samples_per_sec"] = _finite(record.get("fit_samples_per_sec"))
    else:
        summary["mfu"] = _finite(fit_end.get("mfu"))
        summary["fit_samples_per_sec"] = None

    # bench_suite.py rows (one bench_row event each): the full measurement
    # batch — surfaced per row so the catalog-scaling family reads as a table
    summary["bench_rows"] = [
        {
            key: record.get(key)
            for key in (
                "row", "samples_per_sec", "step_ms", "scan_k", "mfu",
                "mfu_peak_assumed", "tflops_per_sec", "num_items", "d", "B",
                "L", "loss", "precision", "model_parallel", "backend", "error",
                # streaming-input rows (stream_{inmem,parquet,packed}): the
                # padding-waste and feed-efficiency measurements
                "effective_tokens_per_sec", "padding_fraction",
                "segments_per_row", "rows_on_disk", "shard",
                # the DP×TP×SP long-context rows: attention route, mesh grid
                # and the remat A/B flag the pair gate keys on
                "attention", "mesh", "remat", "ring_max_err",
                # static program analyses (obs.roofline / parallel.introspect)
                "roofline_bound", "roofline_ceiling_tflops",
                "of_roofline_ceiling", "arithmetic_intensity",
                "hbm_peak_bytes", "collective_bytes", "peak_memory_bytes",
            )
            if key in record
        }
        for record in bench_rows
    ] or None

    # the precision ladder's pair view (prec_{f32,bf16}_<head> bench rows):
    # HBM/step deltas per head, the "each rung must move bytes" evidence.
    # Rendered informationally; the CI gate is --compare's per-row lower-
    # better hbm_peak_bytes on prec_* rows. NOTE the strictly-lower-HBM claim
    # is a TPU claim: the CPU backend materializes f32 converts for bf16
    # programs, so CPU smoke pairs legitimately show no byte win.
    rows_by_name = {
        record.get("row"): record for record in bench_rows if record.get("row")
    }
    pairs: Dict[str, Any] = {}
    for name, row in rows_by_name.items():
        if not name.startswith("prec_bf16_") or row.get("error"):
            continue
        head = name[len("prec_bf16_"):]
        base = rows_by_name.get(f"prec_f32_{head}")
        if not base or base.get("error"):
            continue
        pair: Dict[str, Any] = {
            "f32_hbm_peak_bytes": _finite(base.get("hbm_peak_bytes")),
            "bf16_hbm_peak_bytes": _finite(row.get("hbm_peak_bytes")),
            "f32_step_ms": _finite(base.get("step_ms")),
            "bf16_step_ms": _finite(row.get("step_ms")),
            "backend": row.get("backend"),
        }
        if pair["f32_hbm_peak_bytes"] and pair["bf16_hbm_peak_bytes"] is not None:
            pair["hbm_saved_fraction"] = (
                1.0 - pair["bf16_hbm_peak_bytes"] / pair["f32_hbm_peak_bytes"]
            )
        pairs[head] = pair
    summary["precision_pairs"] = pairs or None

    # the remat pair view (<base>_remat_{off,on} bench rows): activation
    # checkpointing exists to MOVE bytes — the pair is the evidence, and
    # --compare gates remat-on's hbm_peak_bytes below remat-off's (the static
    # memory_analysis holds on CPU too, unlike the bf16 byte claim)
    remat_pairs: Dict[str, Any] = {}
    for name, row in rows_by_name.items():
        if not name.endswith("_remat_on") or row.get("error"):
            continue
        base_name = name[: -len("_remat_on")]
        base = rows_by_name.get(f"{base_name}_remat_off")
        if not base or base.get("error"):
            continue
        pair = {
            "off_hbm_peak_bytes": _finite(base.get("hbm_peak_bytes")),
            "on_hbm_peak_bytes": _finite(row.get("hbm_peak_bytes")),
            "off_step_ms": _finite(base.get("step_ms")),
            "on_step_ms": _finite(row.get("step_ms")),
            "backend": row.get("backend"),
        }
        if pair["off_hbm_peak_bytes"] and pair["on_hbm_peak_bytes"] is not None:
            pair["hbm_saved_fraction"] = (
                1.0 - pair["on_hbm_peak_bytes"] / pair["off_hbm_peak_bytes"]
            )
        remat_pairs[base_name] = pair
    summary["remat_pairs"] = remat_pairs or None

    # peak device memory: fit telemetry first, then the bench record, then the
    # largest non-error suite row — the --compare lower-better gate's input
    peak_memory = _finite(fit_end.get("peak_memory_bytes"))
    if peak_memory is None and bench:
        peak_memory = _finite(bench[-1].get("peak_memory_bytes"))
    if peak_memory is None and bench_rows:
        row_peaks = [
            value
            for row in bench_rows
            if not row.get("error")
            for value in [_finite(row.get("peak_memory_bytes"))]
            if value is not None
        ]
        peak_memory = max(row_peaks) if row_peaks else None
    summary["peak_memory_bytes"] = peak_memory

    if dryruns:
        record = dryruns[-1]
        summary["dryrun"] = {
            key: record.get(key)
            for key in (
                "mesh", "losses", "psum", "sp_ring_err", "spans", "backend",
                "collectives", "sharding", "processes", "mesh3",
            )
            if key in record
        }
        if summary["processes"] is None and isinstance(
            record.get("processes"), Mapping
        ):
            # the dry run measures its per-process timing directly (it emits
            # no per-step events): surface its skew record at the top level
            # so the straggler gate reads dry runs and real fits identically
            summary["processes"] = dict(record["processes"])

    # the serving summary (replay_tpu.serve): service-side totals from the
    # on_serve_end event, load-side qps/latency percentiles from the
    # bench_serve.py record — either alone still renders a section
    serve: Dict[str, Any] = {}
    if serve_ends:
        record = serve_ends[-1]
        serve.update(
            {
                key: record.get(key)
                for key in (
                    "mode", "requests", "answered", "errors", "cache_hit_rate",
                    "pure_hit_rate", "batch_fill_ratio", "queue_wait_ms_mean",
                    "queue_wait_ms_max",
                    # resilience totals (overload/chaos accounting)
                    "shed", "deadline_misses", "cancelled", "circuit_refusals",
                    "degraded", "shed_rate", "deadline_miss_rate", "error_rate",
                )
                if key in record
            }
        )
        if isinstance(record.get("served_by"), Mapping):
            serve["served_by"] = dict(record["served_by"])
        if isinstance(record.get("breaker"), Mapping):
            serve["breaker"] = dict(record["breaker"])
        serve["batches"] = len(serve_batches)
        resilience_counts = {"on_shed": 0, "on_breaker": 0, "on_degrade": 0}
        for e in events:
            name = e.get("event")
            if name in resilience_counts:
                resilience_counts[name] += 1
        serve["shed_events"] = resilience_counts["on_shed"]
        serve["breaker_events"] = resilience_counts["on_breaker"]
        serve["degrade_events"] = resilience_counts["on_degrade"]
    if bench and "serve" in str(bench[-1].get("metric", "")):
        record = bench[-1]
        serve.update(
            {
                key: record.get(key)
                for key in (
                    "qps", "p50_ms", "p95_ms", "p99_ms", "batch_fill_ratio",
                    "cache_hit_rate", "closed_loop_qps", "requests", "mode",
                    "hung_requests",
                )
                if key in record
            }
        )
        # the run-wide rates the --compare lower-better gates consume; the
        # bench record's numbers win over on_serve_end (same totals, rounded)
        for bench_key, serve_key in (
            ("serve_shed_rate", "shed_rate"),
            ("serve_deadline_miss_rate", "deadline_miss_rate"),
            ("serve_error_rate", "error_rate"),
        ):
            if _finite(record.get(bench_key)) is not None:
                serve[serve_key] = float(record[bench_key])
        if isinstance(record.get("served_by"), Mapping):
            serve["served_by"] = dict(record["served_by"])
        if isinstance(record.get("breaker"), Mapping):
            serve["breaker"] = dict(record["breaker"])
        overload = record.get("overload")
        if isinstance(overload, Mapping):
            # the overload flag gates shed-rate comparability: shed rates only
            # mean the same thing between two runs that both ran overload
            serve["overload"] = True
            serve["overload_p99_ms"] = _finite(overload.get("p99_ms"))
            serve["overload_shed_rate"] = _finite(overload.get("shed_rate"))
            serve["overload_deadline_miss_rate"] = _finite(
                overload.get("deadline_miss_rate")
            )
        quant = record.get("quant")
        if isinstance(quant, Mapping):
            # the int8-vs-f32 retrieval A/B (precision ladder's serving
            # rung): recall/topk-match are --compare higher-better gates
            serve["quant"] = {
                key: quant.get(key)
                for key in (
                    "candidates", "top_k", "recall_at_candidates",
                    "topk_match_rate", "f32_rank_ms", "int8_rank_ms",
                    "int8_table_bytes", "f32_table_bytes", "bytes_ratio",
                )
                if key in quant
            }
        ann = record.get("ann")
        if isinstance(ann, Mapping):
            # the IVF sub-linear retrieval phase: recall@100 / topk agreement
            # are --compare higher-better gates (0.005 abs floor, the quant
            # convention); ann_qps is higher-better; the speedup line renders
            # brute-vs-IVF throughput on the same catalog
            serve["ann"] = {
                key: ann.get(key)
                for key in (
                    "items", "dim", "nlist", "nprobe", "cmax",
                    "scanned_fraction", "recall_at_100", "topk_agreement",
                    "ivf_qps", "brute_qps", "speedup", "build_s",
                    "recall_at_100_int8", "recall_at_100_pq",
                    "index_total_bytes", "projection_100m",
                )
                if key in ann
            }
        chaos = record.get("chaos")
        if isinstance(chaos, Mapping):
            serve["chaos"] = {
                key: chaos.get(key)
                for key in (
                    "injected_engine_errors", "breaker_opens",
                    "breaker_state_final", "recovered", "hung_requests",
                    "storm_deadline_missed",
                )
                if key in chaos
            }
        swap = record.get("swap")
        if isinstance(swap, Mapping):
            # the swap-under-load phase (serve.promote): the swap flag gates
            # swap_p99_ms comparability exactly like overload gates shed rate
            serve["swap"] = True
            serve["swap_count"] = swap.get("swaps")
            serve["swap_p99_ms"] = _finite(swap.get("p99_ms"))
            serve["swap_errors"] = swap.get("errors")
            serve["swap_generations"] = swap.get("generations_seen")
            serve["swap_recompiled"] = swap.get("recompiled_swaps")
    summary["serve"] = serve or None

    # the quality plane (obs.quality): the last on_quality_window per role is
    # the run's final windowed telemetry; drift warnings sum their coalesced
    # counts; the bench drift-phase record (bench_serve.py) carries the
    # injected-shift evidence the drift_psi --compare gate is phase-matched on
    quality_windows = [e for e in events if e.get("event") == "on_quality_window"]
    drift_warning_events = [
        e for e in events if e.get("event") == "on_drift_warning"
    ]
    quality: Dict[str, Any] = {}
    if quality_windows or drift_warning_events:
        quality["windows"] = len(quality_windows)
        quality["drift_warnings"] = sum(
            int(e.get("count") or 1) for e in drift_warning_events
        )
        roles: Dict[str, Any] = {}
        for e in quality_windows:
            roles[str(e.get("role") or "stable")] = {
                key: e.get(key)
                for key in (
                    "requests", "k", "joins", "coverage", "novelty",
                    "surprisal", "popularity", "ild", "score_entropy",
                    "top1_margin", "online_hitrate", "online_mrr",
                    "online_ndcg", "online_hitrate_cum", "online_mrr_cum",
                    "online_ndcg_cum",
                )
                if key in e
            }
        quality["roles"] = roles
        # the stable slice's cumulative prequential metrics at the top level:
        # what the higher-better online_hitrate gate reads
        stable = roles.get("stable") or next(iter(roles.values()), {})
        for key in (
            "k", "joins", "online_hitrate_cum", "online_mrr_cum",
            "online_ndcg_cum",
        ):
            if stable.get(key) is not None:
                quality[key] = stable.get(key)
        psi_values = [
            value
            for e in quality_windows
            if isinstance(e.get("drift"), Mapping)
            for value in (_finite(e["drift"].get("max")),)
            if value is not None
        ]
        if psi_values:
            quality["drift_psi"] = psi_values[-1]
            quality["drift_psi_peak"] = max(psi_values)
        if drift_warning_events:
            quality["drift_series"] = sorted(
                {
                    str(e.get("series"))
                    for e in drift_warning_events
                    if e.get("series") is not None
                }
            )
    if bench and "serve" in str(bench[-1].get("metric", "")):
        drift_record = bench[-1].get("drift")
        if isinstance(drift_record, Mapping):
            # the injected preference-shift phase ran: psi/violations are
            # meaningful and the lower-better drift_psi gate may apply
            quality["drift_phase"] = True
            for src, dst in (
                ("slo_violations", "drift_slo_violations"),
                ("warnings", "drift_phase_warnings"),
                ("psi_peak", "drift_psi_peak"),
                ("shift_fraction", "drift_shift_fraction"),
            ):
                if drift_record.get(src) is not None:
                    quality[dst] = drift_record.get(src)
    summary["quality"] = quality or None

    # the fleet summary (serve.fleet): router-level health/failover/hedge
    # events plus the bench_fleet.py record — per-replica serve totals come
    # from the merged per-replica event shards (each replica logs through
    # JsonlLogger(process_index=i), the PR-10 multi-host machinery reused
    # one level up)
    health_events = [e for e in events if e.get("event") == "on_replica_health"]
    failover_events = [e for e in events if e.get("event") == "on_failover"]
    hedge_events = [e for e in events if e.get("event") == "on_hedge"]
    fleet_ends = [e for e in events if e.get("event") == "on_fleet_end"]
    fleet_bench = bench[-1] if bench and "fleet" in str(bench[-1].get("metric", "")) else None
    fleet: Dict[str, Any] = {}
    if health_events or failover_events or fleet_ends or fleet_bench is not None:
        fleet["health_transitions"] = len(health_events)
        fleet["failover_events"] = len(failover_events)
        fleet["hedge_events"] = len(hedge_events)
        by_replica: Dict[str, List[str]] = {}
        for e in health_events:
            replica = str(e.get("replica"))
            by_replica.setdefault(replica, []).append(
                f"{e.get('from')}->{e.get('to')}"
                + (f"({e.get('reason')})" if e.get("reason") else "")
            )
        if by_replica:
            fleet["replica_transitions"] = {
                replica: moves for replica, moves in sorted(by_replica.items())
            }
        if fleet_ends:
            record = fleet_ends[-1]
            for key in (
                "replicas", "requests", "answered", "errors", "reroutes",
                "retries", "hedges", "hedge_wins", "hedge_cancelled",
                "failovers", "reroute_rate", "error_rate", "p50_ms", "p99_ms",
            ):
                if _finite(record.get(key)) is not None:
                    fleet[key] = record.get(key)
        # per-replica serve totals from the merged shards: each replica's own
        # on_serve_end, keyed by its shard's process_index — renamed to the
        # replica id when the bench record carries the shard map
        shard_names: Dict[str, str] = {}
        if fleet_bench is not None and isinstance(
            fleet_bench.get("replica_shards"), Mapping
        ):
            shard_names = {
                str(k): str(v) for k, v in fleet_bench["replica_shards"].items()
            }
        per_replica: Dict[str, Any] = {}
        for e in serve_ends:
            pid = e.get("process_index")
            if pid is None:
                continue
            per_replica[shard_names.get(str(pid), str(pid))] = {
                key: e.get(key)
                for key in (
                    "requests", "answered", "cache_hit_rate", "error_rate",
                    "shed", "degraded",
                )
                if key in e
            }
        if fleet_ends:
            # per-replica ROUTER counters from the final fleet stats: hedges
            # LANDED on each replica as the racing twin, hedge wins/cancels,
            # and retries each replica's refusals caused — merged into the
            # same per-replica map the serve-side shards fill ("answered"
            # stays serve-side: the router's count excludes lost hedge twins)
            router_stats = fleet_ends[-1].get("per_replica")
            if isinstance(router_stats, Mapping):
                for replica, stats in router_stats.items():
                    if not isinstance(stats, Mapping):
                        continue
                    dest = per_replica.setdefault(str(replica), {})
                    for key in (
                        "routed", "hedges", "hedge_wins", "hedge_cancelled",
                        "retries",
                    ):
                        if _finite(stats.get(key)) is not None:
                            dest[key] = stats.get(key)
            # the exemplar store: the slowest answered requests with their
            # trace ids — the report's link from "p99 is slow" to the exact
            # timelines in the merged trace.json
            exemplars = fleet_ends[-1].get("latency_exemplars")
            if isinstance(exemplars, (list, tuple)) and exemplars:
                fleet["latency_exemplars"] = [
                    {
                        "latency_ms": e.get("latency_ms"),
                        "trace_id": e.get("trace_id"),
                    }
                    for e in exemplars
                    if isinstance(e, Mapping)
                ]
        if per_replica:
            fleet["per_replica"] = per_replica
        if fleet_bench is not None:
            for key in (
                "qps", "p50_ms", "p99_ms", "replicas", "requests",
                "reroutes", "reroute_rate", "cache_hit_locality",
                "failover_gap_ms", "hung_requests", "fleet_error_rate",
                "single_replica_qps", "single_replica_hit_rate",
            ):
                if fleet_bench.get(key) is not None:
                    fleet[key] = fleet_bench.get(key)
            chaos = fleet_bench.get("chaos")
            if isinstance(chaos, Mapping):
                fleet["chaos"] = {
                    key: chaos.get(key)
                    for key in (
                        "killed", "revived", "failover_gap_ms", "reroutes",
                        "hung_requests", "error_rate", "failover_answers",
                        "failover_served_by", "exemplar_trace_ids",
                    )
                    if key in chaos
                }
            drain_swap = fleet_bench.get("drain_swap")
            if isinstance(drain_swap, Mapping):
                fleet["drain_swap"] = {
                    key: drain_swap.get(key)
                    for key in (
                        "replicas_swapped", "drained", "errors", "generations",
                        "p99_ms",
                    )
                    if key in drain_swap
                }
            per_replica_bench = fleet_bench.get("per_replica")
            if isinstance(per_replica_bench, Mapping):
                for replica, stats in per_replica_bench.items():
                    if isinstance(stats, Mapping):
                        fleet.setdefault("per_replica", {}).setdefault(
                            str(replica), {}
                        ).update(stats)
    summary["fleet"] = fleet or None
    return summary


# --------------------------------------------------------------------------- #
# rendering
# --------------------------------------------------------------------------- #
def _fmt(value: Optional[float], pattern: str = "{:.1f}", missing: str = "–") -> str:
    return pattern.format(value) if value is not None else missing


def render(summary: Mapping[str, Any]) -> str:
    lines = [f"Run report — {summary.get('source') or '<events>'}"]
    backend = f" · backend={summary['backend']}" if summary.get("backend") else ""
    lines.append(
        f"  kind: {summary.get('kind')} · events: {summary.get('events')}{backend}"
    )
    throughput = summary.get("samples_per_sec")
    if throughput is not None or summary.get("steps_per_sec") is not None:
        source = summary.get("throughput_source")
        lines.append(
            "  throughput: "
            f"{_fmt(throughput)} samples/sec"
            f" ({_fmt(summary.get('steps_per_sec'), '{:.2f}')} steps/sec)"
            + (f" [{source}]" if source else "")
            + (f" · MFU {_fmt(summary.get('mfu'), '{:.3f}')}" if summary.get("mfu") is not None else "")
        )
    if summary.get("train_steps") or summary.get("epochs"):
        lines.append(
            f"  training: {summary.get('epochs', 0)} epoch(s) · "
            f"{summary.get('train_steps', 0)} step event(s) · "
            f"final train_loss { _fmt(summary.get('final_train_loss'), '{:.4f}') }"
        )
    if "retraces" in summary:
        per_fn = " · ".join(
            f"{name}:{entry.get('traces')}x/{entry.get('compile_seconds', 0):.2f}s"
            for name, entry in sorted(summary.get("compile", {}).items())
            if isinstance(entry, Mapping)
        )
        lines.append(
            f"  compile: {summary['retraces']} retrace(s), "
            f"{summary.get('compile_seconds', 0.0):.2f}s total ({per_fn})"
        )
    reliability = [
        f"bad_steps={summary['bad_steps']}" if summary.get("bad_steps") is not None else None,
        f"anomalies={summary.get('anomalies', 0)}",
        f"recoveries={summary.get('recoveries', 0)}",
        f"preemptions={summary.get('preemptions', 0)}",
        (
            f"last_grad_norm={summary['last_grad_norm']:.3g}"
            if summary.get("last_grad_norm") is not None
            else None
        ),
    ]
    lines.append("  reliability: " + " ".join(part for part in reliability if part))
    if summary.get("slo_violations") or summary.get("slo_recoveries"):
        fired = summary.get("slo_rules_fired") or []
        lines.append(
            f"  SLO: {summary.get('slo_violations', 0)} violation(s), "
            f"{summary.get('slo_recoveries', 0)} recovered"
            + (f" — rules: {', '.join(fired)}" if fired else "")
        )
    promotion = summary.get("promotion")
    if promotion:
        parts = [
            f"{promotion.get('publishes', 0)} publish(es)"
            + (
                f" ({promotion['recompiled_publishes']} recompiled)"
                if promotion.get("recompiled_publishes")
                else ""
            ),
            f"{promotion.get('canaries', 0)} canary(ies)",
            f"{promotion.get('canary_evals', 0)} eval(s)",
            f"{promotion.get('promotions', 0)} promoted",
            f"{promotion.get('rollbacks', 0)} rolled back",
        ]
        if promotion.get("last_generation") is not None:
            parts.append(f"serving generation {promotion['last_generation']}")
        lines.append("  promotion: " + " · ".join(parts))
        if promotion.get("rollback_rules"):
            lines.append(
                "    rollback rule(s): " + ", ".join(promotion["rollback_rules"])
            )
    processes = summary.get("processes")
    if processes:
        per_host = processes.get("step_seconds") or {}
        shown = " · ".join(
            f"p{pid} {1000.0 * float(value):.2f}ms" for pid, value in per_host.items()
        )
        index = _finite(processes.get("straggler_index"))
        skew = _finite(processes.get("skew"))
        lines.append(
            f"  processes: {processes.get('count')} host(s)"
            + (f" · straggler index {index:.3f} (p{processes.get('straggler')})" if index is not None else "")
            + (f" · skew {skew:.3f}" if skew is not None else "")
            + (f" · step time {shown}" if shown else "")
        )
    health = summary.get("health")
    if health:
        parts = []
        value = _finite(health.get("grad_norm_global"))
        if value is not None:
            parts.append(f"grad_norm {value:.3g}")
        ratios = health.get("update_ratio")
        if isinstance(ratios, Mapping):
            finite = {
                name: v for name, v in ((n, _finite(r)) for n, r in ratios.items()) if v is not None
            }
            if finite:
                worst = max(finite, key=finite.get)
                parts.append(f"max update_ratio {finite[worst]:.3g} ({worst})")
        value = _finite(health.get("attention_entropy_mean"))
        if value is not None:
            parts.append(f"attn entropy {value:.3f} nats")
        value = _finite(health.get("embedding_coverage"))
        if value is not None:
            parts.append(f"emb coverage {100.0 * value:.0f}%")
        logits = health.get("logits")
        if isinstance(logits, Mapping) and _finite(logits.get("absmax")) is not None:
            parts.append(f"logits absmax {_finite(logits.get('absmax')):.3g}")
        parts.append(f"warnings {summary.get('health_warnings', 0)}")
        lines.append("  model health: " + " · ".join(parts))
        norms = health.get("grad_norm")
        if isinstance(norms, Mapping) and norms:
            shown = " · ".join(
                f"{name} {_fmt(_finite(v), '{:.3g}')}" for name, v in sorted(norms.items())
            )
            lines.append(f"    group grad norms: {shown}")
        activations = health.get("activations")
        if isinstance(activations, Mapping) and activations:
            shown = " · ".join(
                f"{stage} rms {_fmt(_finite(stats.get('rms')), '{:.3g}')}"
                f"/max {_fmt(_finite(stats.get('absmax')), '{:.3g}')}"
                for stage, stats in sorted(activations.items())
                if isinstance(stats, Mapping)
            )
            lines.append(f"    activations: {shown}")
    elif summary.get("health_warnings"):
        lines.append(f"  model health: warnings {summary['health_warnings']}")
    goodput = summary.get("goodput")
    if goodput:
        fractions = goodput.get("fractions") or {}
        # training and serving breakdowns carry different span sets; show
        # whichever phases this run recorded, in canonical order
        phase_order = (
            *GOODPUT_SPANS,
            *(n for n in SERVE_GOODPUT_SPANS if n not in GOODPUT_SPANS),
            "other",
        )
        shown = " · ".join(
            f"{name} {100.0 * float(fractions.get(name, 0.0)):.1f}%"
            for name in phase_order
            if name in fractions
        )
        lines.append(
            f"  goodput (wall {_fmt(_finite(goodput.get('wall_seconds')), '{:.2f}')}s): {shown}"
        )
        starvation = _finite(goodput.get("input_starvation"))
        if starvation is not None:
            lines.append(
                f"  input starvation: {100.0 * starvation:.1f}% of the stepping pipeline"
            )
        h2d_seconds = _finite(summary.get("h2d_seconds"))
        wall = _finite(goodput.get("wall_seconds"))
        if h2d_seconds is not None and wall:
            # chunked runs place chunks on the device-feed thread: the share
            # of h2d NOT in the fit loop's fractions overlapped compute
            in_loop = float(fractions.get("h2d", 0.0)) * wall
            overlapped = max(h2d_seconds - in_loop, 0.0)
            lines.append(
                f"  h2d: {h2d_seconds:.2f}s across threads — "
                f"{overlapped:.2f}s overlapped on the device feed, "
                f"{in_loop:.2f}s in the fit loop"
            )
    input_record = summary.get("input")
    if input_record:
        parts = []
        padding = _finite(input_record.get("padding_fraction"))
        if padding is not None:
            parts.append(f"padding {100.0 * padding:.1f}%")
        effective = _finite(input_record.get("effective_tokens_per_sec"))
        if effective is not None:
            parts.append(f"effective tokens/s {effective:,.0f}")
        tokens_real = _finite(input_record.get("tokens_real"))
        tokens_grid = _finite(input_record.get("tokens_grid"))
        if tokens_real is not None and tokens_grid is not None:
            parts.append(f"tokens {tokens_real:,.0f}/{tokens_grid:,.0f}")
        if parts:
            lines.append("  input feed: " + " · ".join(parts))
    trace = summary.get("trace")
    if trace:
        top = sorted(trace.items(), key=lambda kv: -kv[1]["seconds"])[:8]
        shown = " · ".join(
            f"{name} {entry['seconds']:.2f}s x{entry['count']}" for name, entry in top
        )
        lines.append(f"  trace.json: {sum(e['count'] for e in trace.values())} span(s): {shown}")
    device_time = summary.get("device_time")
    if device_time:
        total = _finite(device_time.get("total_device_seconds")) or 0.0
        scopes = device_time.get("scopes") or {}
        parts = [
            f"{scope} {100.0 * float((entry or {}).get('fraction', 0.0)):.1f}%"
            for scope, entry in scopes.items()
            if isinstance(entry, Mapping)
        ]
        unattributed = _finite(device_time.get("unattributed_seconds"))
        if unattributed is not None and total > 0:
            parts.append(f"unattributed {100.0 * unattributed / total:.1f}%")
        lines.append(
            f"  device attribution ({1000.0 * total:.1f} ms device time in the "
            "profiled window): " + (" · ".join(parts) if parts else "no scopes resolved")
        )
    roofline = summary.get("roofline")
    if roofline:
        lines.append("  roofline:")
        for program, record in sorted(roofline.items()):
            if not isinstance(record, Mapping):
                continue
            classification = record.get("roofline") or {}
            parts = []
            if classification.get("bound"):
                assumed = classification.get("peak_assumed")
                parts.append(
                    f"{classification['bound']}-bound"
                    + (f" (assumed {assumed} peaks)" if assumed else "")
                )
                intensity = _finite(classification.get("arithmetic_intensity"))
                critical = _finite(classification.get("critical_intensity"))
                if intensity is not None and critical is not None:
                    parts.append(
                        f"intensity {intensity:.1f} flops/B (critical {critical:.1f})"
                    )
                ceiling = _finite(classification.get("ceiling_tflops"))
                if ceiling is not None:
                    parts.append(f"ceiling {ceiling:.3g} TFLOP/s")
            else:
                parts.append("unclassified (no chip peaks)")
            peak = _finite(record.get("hbm_peak_bytes"))
            if peak is not None:
                parts.append(f"peak HBM {peak / 1e6:.1f} MB")
            collective = _finite(record.get("collective_bytes"))
            if collective is not None:
                parts.append(f"collectives {collective / 1e6:.2f} MB")
            lines.append(f"    {program}: " + " · ".join(parts))
    dryrun = summary.get("dryrun")
    if dryrun:
        lines.append(
            f"  dryrun_multichip: mesh={dryrun.get('mesh')} losses={dryrun.get('losses')} "
            f"psum={dryrun.get('psum')} sp_ring_err={dryrun.get('sp_ring_err')}"
        )
        if dryrun.get("spans"):
            shown = " · ".join(
                f"{name} {entry.get('seconds', 0.0):.2f}s"
                for name, entry in sorted(dryrun["spans"].items())
            )
            lines.append(f"  dryrun spans: {shown}")
        collectives = dryrun.get("collectives")
        if isinstance(collectives, Mapping):
            for program, entry in sorted(collectives.items()):
                if not isinstance(entry, Mapping):
                    continue
                by_op = entry.get("by_op") or {}
                shown = " · ".join(
                    f"{op} x{stats.get('count')} ({(stats.get('bytes') or 0) / 1e3:.1f} kB)"
                    for op, stats in sorted(by_op.items())
                    if isinstance(stats, Mapping)
                )
                lines.append(
                    f"  collectives[{program}]: {entry.get('count')} op(s), "
                    f"{(entry.get('bytes') or 0) / 1e3:.1f} kB: {shown}"
                )
        sharding = dryrun.get("sharding")
        if isinstance(sharding, Mapping):
            flags = sharding.get("flags") or []
            lines.append(
                f"  sharding: {(sharding.get('sharded_bytes') or 0) / 1e3:.1f} kB "
                f"sharded · {(sharding.get('replicated_bytes') or 0) / 1e3:.1f} kB "
                f"replicated · {len(flags)} flag(s)"
            )
            for flag in flags:
                lines.append(f"    FLAG: {flag}")
    bench = summary.get("bench")
    if bench:
        lines.append(
            f"  bench: {bench.get('metric')} = {bench.get('value')} {bench.get('unit', '')}"
            + (f" (vs_baseline {bench.get('vs_baseline')})" if "vs_baseline" in bench else "")
            + (" [stale sidecar]" if bench.get("stale") else "")
        )
        if bench.get("fit_samples_per_sec") is not None:
            gap = bench.get("dispatch_gap_closed")
            lines.append(
                f"  fit loop: {bench['fit_samples_per_sec']} samples/sec "
                f"({bench.get('fit_step_ms')} ms/step, "
                f"scan_chunk={bench.get('fit_scan_chunk')}, "
                f"device_feed={bench.get('fit_device_feed')})"
                + (
                    f" · dispatch gap closed {100.0 * float(gap):.0f}%"
                    if isinstance(gap, (int, float)) and not isinstance(gap, bool)
                    else ""
                )
            )
    bench_rows = summary.get("bench_rows")
    if bench_rows:
        lines.append(f"  bench suite: {len(bench_rows)} row(s)")
        for row in bench_rows:
            if row.get("error"):
                lines.append(f"    {row.get('row')}: ERROR {row['error']}")
                continue
            parts = [
                f"{_fmt(_finite(row.get('step_ms')), '{:.3f}')} ms/step",
                f"{_fmt(_finite(row.get('samples_per_sec')))} samples/sec",
            ]
            utilization = _finite(row.get("mfu"))
            if utilization is not None:
                assumed = row.get("mfu_peak_assumed")
                parts.append(
                    f"MFU {utilization:.4g}"
                    + (f" (assumed {assumed} peak)" if assumed else "")
                )
            if row.get("num_items") is not None:
                parts.append(f"items {row['num_items']}")
            if row.get("loss"):
                parts.append(str(row["loss"]))
            if row.get("precision"):
                parts.append(f"prec {row['precision']}")
            if row.get("roofline_bound"):
                bound = f"{row['roofline_bound']}-bound"
                of_ceiling = _finite(row.get("of_roofline_ceiling"))
                if of_ceiling is not None:
                    bound += f" ({100.0 * of_ceiling:.0f}% of ceiling)"
                parts.append(bound)
            hbm = _finite(row.get("hbm_peak_bytes"))
            if hbm is not None:
                parts.append(f"HBM {hbm / 1e6:.1f} MB")
            collective = _finite(row.get("collective_bytes"))
            if collective:
                parts.append(f"coll {collective / 1e6:.2f} MB")
            effective = _finite(row.get("effective_tokens_per_sec"))
            if effective is not None:
                parts.append(f"eff tokens/s {effective:,.0f}")
            padding = _finite(row.get("padding_fraction"))
            if padding is not None:
                parts.append(f"padding {100.0 * padding:.1f}%")
            segments = _finite(row.get("segments_per_row"))
            if segments is not None:
                parts.append(f"{segments:.2f} seg/row")
            lines.append(f"    {row.get('row')}: " + " · ".join(parts))
    precision_pairs = summary.get("precision_pairs")
    if precision_pairs:
        for head, pair in sorted(precision_pairs.items()):
            if not isinstance(pair, Mapping):
                continue
            parts = []
            f32_hbm, bf16_hbm = pair.get("f32_hbm_peak_bytes"), pair.get("bf16_hbm_peak_bytes")
            if f32_hbm is not None and bf16_hbm is not None:
                parts.append(f"HBM {f32_hbm / 1e6:.1f}→{bf16_hbm / 1e6:.1f} MB")
                saved = pair.get("hbm_saved_fraction")
                if saved is not None:
                    parts.append(f"({saved:+.1%} saved)")
            f32_ms, bf16_ms = pair.get("f32_step_ms"), pair.get("bf16_step_ms")
            if f32_ms is not None and bf16_ms is not None:
                parts.append(f"step {f32_ms:.3f}→{bf16_ms:.3f} ms")
            if pair.get("backend") == "cpu":
                # the byte win is a TPU claim: CPU materializes f32 converts
                parts.append("[cpu smoke: byte win not expected]")
            lines.append(f"  precision ladder [{head}]: " + " · ".join(parts))
    remat_pairs = summary.get("remat_pairs")
    if remat_pairs:
        for base_name, pair in sorted(remat_pairs.items()):
            if not isinstance(pair, Mapping):
                continue
            parts = []
            off_hbm, on_hbm = pair.get("off_hbm_peak_bytes"), pair.get("on_hbm_peak_bytes")
            if off_hbm is not None and on_hbm is not None:
                parts.append(f"HBM {off_hbm / 1e6:.1f}→{on_hbm / 1e6:.1f} MB")
                saved = pair.get("hbm_saved_fraction")
                if saved is not None:
                    parts.append(f"({saved:+.1%} saved)")
            off_ms, on_ms = pair.get("off_step_ms"), pair.get("on_step_ms")
            if off_ms is not None and on_ms is not None:
                parts.append(f"step {off_ms:.3f}→{on_ms:.3f} ms")
            lines.append(f"  remat [{base_name}]: " + " · ".join(parts))
    serve = summary.get("serve")
    if serve:
        parts = []
        if _finite(serve.get("qps")) is not None:
            parts.append(f"{serve['qps']:.1f} qps")
        if _finite(serve.get("p50_ms")) is not None:
            parts.append(
                f"latency p50/p95/p99 {_fmt(_finite(serve.get('p50_ms')), '{:.2f}')}"
                f"/{_fmt(_finite(serve.get('p95_ms')), '{:.2f}')}"
                f"/{_fmt(_finite(serve.get('p99_ms')), '{:.2f}')} ms"
            )
        if serve.get("requests") is not None:
            answered = serve.get("answered")
            parts.append(
                f"requests {serve['requests']}"
                + (f" ({answered} answered)" if answered is not None else "")
            )
        if _finite(serve.get("batch_fill_ratio")) is not None:
            parts.append(f"batch fill {100.0 * serve['batch_fill_ratio']:.0f}%")
        if _finite(serve.get("cache_hit_rate")) is not None:
            parts.append(f"cache hits {100.0 * serve['cache_hit_rate']:.0f}%")
        if _finite(serve.get("queue_wait_ms_mean")) is not None:
            parts.append(f"queue wait {serve['queue_wait_ms_mean']:.2f} ms mean")
        mode = f" [{serve['mode']}]" if serve.get("mode") else ""
        lines.append(f"  serving{mode}: " + " · ".join(parts))
        # the resilience line: shed / deadline-miss / error rates, degraded
        # traffic by ladder rung, breaker state — overload/chaos evidence
        rates = [
            (label, _finite(serve.get(key)))
            for label, key in (
                ("shed", "shed_rate"),
                ("deadline-miss", "deadline_miss_rate"),
                ("error", "error_rate"),
            )
        ]
        if any(value is not None for _, value in rates):
            parts = [
                f"{label} rate {value:.2%}" for label, value in rates if value is not None
            ]
            served_by = serve.get("served_by")
            if isinstance(served_by, Mapping):
                degraded = sum(
                    int(count) for rung, count in served_by.items() if rung != "primary"
                )
                shown = "/".join(
                    f"{rung}:{served_by[rung]}" for rung in ("cache_only", "fallback")
                    if rung in served_by
                )
                parts.append(f"degraded {degraded}" + (f" ({shown})" if shown else ""))
            breaker = serve.get("breaker")
            if isinstance(breaker, Mapping):
                parts.append(
                    f"breaker {breaker.get('state')} "
                    f"({breaker.get('opens', 0)} open(s))"
                )
            if serve.get("hung_requests") is not None:
                parts.append(f"hung {serve['hung_requests']}")
            lines.append("  serving resilience: " + " · ".join(parts))
        if serve.get("overload"):
            parts = []
            if serve.get("overload_p99_ms") is not None:
                parts.append(f"p99 {serve['overload_p99_ms']:.2f} ms")
            if serve.get("overload_shed_rate") is not None:
                parts.append(f"shed {serve['overload_shed_rate']:.2%}")
            if serve.get("overload_deadline_miss_rate") is not None:
                parts.append(f"deadline-miss {serve['overload_deadline_miss_rate']:.2%}")
            lines.append("  serving overload: " + " · ".join(parts))
        quant = serve.get("quant")
        if isinstance(quant, Mapping):
            parts = []
            recall = _finite(quant.get("recall_at_candidates"))
            if recall is not None:
                parts.append(
                    f"int8 recall@{quant.get('candidates')} {recall:.4f}"
                )
            match = _finite(quant.get("topk_match_rate"))
            if match is not None:
                parts.append(f"top-{quant.get('top_k')} match {match:.4f}")
            if _finite(quant.get("int8_rank_ms")) is not None:
                parts.append(
                    f"rank {quant['int8_rank_ms']:.2f} ms int8 vs "
                    f"{_fmt(_finite(quant.get('f32_rank_ms')), '{:.2f}')} ms f32"
                )
            ratio = _finite(quant.get("bytes_ratio"))
            if ratio is not None:
                parts.append(f"table bytes ×{ratio:.3f}")
            lines.append("  serving quant (int8 retrieval): " + " · ".join(parts))
        ann = serve.get("ann")
        if isinstance(ann, Mapping):
            parts = []
            if ann.get("items") is not None:
                parts.append(
                    f"{ann['items'] / 1e6:.0f}M items · nlist {ann.get('nlist')} "
                    f"· nprobe {ann.get('nprobe')}"
                )
            recall = _finite(ann.get("recall_at_100"))
            if recall is not None:
                parts.append(f"recall@100 {recall:.4f}")
            agreement = _finite(ann.get("topk_agreement"))
            if agreement is not None:
                parts.append(f"top-k agreement {agreement:.4f}")
            speedup = _finite(ann.get("speedup"))
            if speedup is not None:
                parts.append(
                    f"brute {_fmt(_finite(ann.get('brute_qps')), '{:.0f}')} qps "
                    f"vs IVF {_fmt(_finite(ann.get('ivf_qps')), '{:.0f}')} qps "
                    f"(×{speedup:.1f})"
                )
            frac = _finite(ann.get("scanned_fraction"))
            if frac is not None:
                parts.append(f"scans {frac:.2%}/query")
            lines.append("  serving ann (ivf retrieval): " + " · ".join(parts))
        chaos = serve.get("chaos")
        if isinstance(chaos, Mapping):
            lines.append(
                "  serving chaos: "
                f"{chaos.get('injected_engine_errors', 0)} injected error(s) · "
                f"breaker opened {chaos.get('breaker_opens', 0)}x, "
                f"final {chaos.get('breaker_state_final')} · "
                f"storm missed {chaos.get('storm_deadline_missed', 0)} · "
                f"hung {chaos.get('hung_requests', 0)}"
            )
        if serve.get("swap"):
            parts = [f"{serve.get('swap_count', 0)} hot swap(s) under load"]
            if serve.get("swap_recompiled"):
                parts.append(f"{serve['swap_recompiled']} recompiled")
            if serve.get("swap_p99_ms") is not None:
                parts.append(f"p99 {serve['swap_p99_ms']:.2f} ms")
            parts.append(f"errors {serve.get('swap_errors', 0)}")
            if serve.get("swap_generations") is not None:
                parts.append(f"{serve['swap_generations']} generation(s) observed")
            lines.append("  serving swap: " + " · ".join(parts))
    quality = summary.get("quality")
    if quality:
        roles = quality.get("roles") or {}
        for role in sorted(roles):
            stats = roles[role]
            parts = []
            hitrate = _finite(stats.get("online_hitrate_cum"))
            if hitrate is not None:
                parts.append(
                    f"online hitrate@{stats.get('k')} {hitrate:.4f}"
                    + (
                        f" ({stats['joins']} joins)"
                        if stats.get("joins") is not None
                        else ""
                    )
                )
            ndcg = _finite(stats.get("online_ndcg_cum"))
            if ndcg is not None:
                parts.append(f"ndcg {ndcg:.4f}")
            for label, key in (
                ("coverage", "coverage"),
                ("novelty", "novelty"),
                ("surprisal", "surprisal"),
                ("ild", "ild"),
            ):
                value = _finite(stats.get(key))
                if value is not None:
                    parts.append(f"{label} {value:.3f}")
            lines.append(
                f"  quality[{role}]: " + (" · ".join(parts) if parts else "no windows")
            )
        drift_parts = []
        psi = _finite(quality.get("drift_psi"))
        if psi is not None:
            drift_parts.append(f"psi {psi:.3f}")
        peak = _finite(quality.get("drift_psi_peak"))
        if peak is not None:
            drift_parts.append(f"peak {peak:.3f}")
        drift_parts.append(f"{quality.get('drift_warnings', 0)} warning(s)")
        if quality.get("drift_series"):
            drift_parts.append("series " + ",".join(quality["drift_series"]))
        if quality.get("drift_phase"):
            drift_parts.append(
                f"injected-shift phase: {quality.get('drift_slo_violations', 0)} "
                "SLO violation(s)"
            )
        lines.append("  quality drift: " + " · ".join(drift_parts))
    fleet = summary.get("fleet")
    if fleet:
        parts = []
        if fleet.get("replicas") is not None:
            parts.append(f"{fleet['replicas']} replica(s)")
        if _finite(fleet.get("qps")) is not None:
            parts.append(f"{fleet['qps']:.1f} qps aggregate")
        if _finite(fleet.get("p50_ms")) is not None or _finite(fleet.get("p99_ms")) is not None:
            parts.append(
                f"latency p50/p99 {_fmt(_finite(fleet.get('p50_ms')), '{:.2f}')}"
                f"/{_fmt(_finite(fleet.get('p99_ms')), '{:.2f}')} ms"
            )
        if _finite(fleet.get("reroute_rate")) is not None:
            parts.append(f"reroute rate {fleet['reroute_rate']:.2%}")
        locality = _finite(fleet.get("cache_hit_locality"))
        if locality is not None:
            parts.append(f"cache-hit locality {locality:.3f}x single replica")
        lines.append("  fleet: " + (" · ".join(parts) if parts else "events only"))
        health_parts = [
            f"{fleet.get('health_transitions', 0)} health transition(s)",
            f"{fleet.get('failover_events', 0)} failover event(s)",
        ]
        if fleet.get("hedges") is not None or fleet.get("hedge_events"):
            hedges = fleet.get("hedges", fleet.get("hedge_events", 0))
            health_parts.append(
                f"hedges {hedges}"
                + (
                    f" ({fleet['hedge_wins']} won)"
                    if fleet.get("hedge_wins") is not None
                    else ""
                )
            )
        if fleet.get("retries") is not None:
            health_parts.append(f"retries {fleet['retries']}")
        lines.append("  fleet health: " + " · ".join(health_parts))
        transitions = fleet.get("replica_transitions")
        if isinstance(transitions, Mapping):
            for replica, moves in transitions.items():
                lines.append(f"    {replica}: " + " · ".join(moves))
        per_replica = fleet.get("per_replica")
        if isinstance(per_replica, Mapping) and per_replica:
            shown = " · ".join(
                f"{replica} "
                + "/".join(
                    part
                    for part in (
                        f"{stats['qps']:.0f}qps" if _finite(stats.get("qps")) is not None else None,
                        f"p99 {stats['p99_ms']:.1f}ms" if _finite(stats.get("p99_ms")) is not None else None,
                        f"{stats['answered']}ans" if stats.get("answered") is not None else None,
                        f"hits {stats['cache_hit_rate']:.0%}" if _finite(stats.get("cache_hit_rate")) is not None else None,
                        (
                            f"hedges {stats['hedges']}"
                            + (
                                f"({stats['hedge_wins']}w/{stats['hedge_cancelled']}c)"
                                if stats.get("hedge_wins") is not None
                                or stats.get("hedge_cancelled") is not None
                                else ""
                            )
                        )
                        if stats.get("hedges") is not None
                        else None,
                        f"retries {stats['retries']}" if stats.get("retries") is not None else None,
                    )
                    if part
                )
                for replica, stats in sorted(per_replica.items())
                if isinstance(stats, Mapping)
            )
            lines.append(f"  fleet replicas: {shown}")
        exemplars = fleet.get("latency_exemplars")
        if isinstance(exemplars, (list, tuple)) and exemplars:
            lines.append(
                "  fleet exemplars (slowest): "
                + " · ".join(
                    f"{_fmt(_finite(e.get('latency_ms')), '{:.1f}')}ms {e.get('trace_id')}"
                    for e in exemplars[:4]
                    if isinstance(e, Mapping)
                )
            )
        chaos = fleet.get("chaos")
        if isinstance(chaos, Mapping):
            parts = []
            if chaos.get("killed") is not None:
                parts.append(f"killed {chaos['killed']}")
            gap = _finite(chaos.get("failover_gap_ms"))
            if gap is not None:
                parts.append(f"failover gap {gap:.1f} ms")
            if chaos.get("reroutes") is not None:
                parts.append(f"reroutes {chaos['reroutes']}")
            if chaos.get("revived") is not None:
                parts.append(f"revived {chaos['revived']}")
            parts.append(f"hung {chaos.get('hung_requests', 0)}")
            trace_ids = chaos.get("exemplar_trace_ids")
            if isinstance(trace_ids, (list, tuple)) and trace_ids:
                parts.append("traces " + ",".join(str(t) for t in trace_ids[:3]))
            lines.append("  fleet chaos: " + " · ".join(parts))
        drain_swap = fleet.get("drain_swap")
        if isinstance(drain_swap, Mapping):
            lines.append(
                "  fleet rollout: "
                f"{drain_swap.get('replicas_swapped', 0)} replica(s) drained+swapped · "
                f"errors {drain_swap.get('errors', 0)}"
                + (
                    f" · p99 {drain_swap['p99_ms']:.2f} ms"
                    if _finite(drain_swap.get("p99_ms")) is not None
                    else ""
                )
            )
    attribution = summary.get("tail_attribution")
    if isinstance(attribution, Mapping) and isinstance(
        attribution.get("quantiles"), Mapping
    ):
        lines.append(
            f"  tail attribution ({attribution.get('requests', 0)} traced "
            "request(s)):"
        )
        for label, entry in attribution["quantiles"].items():
            if not isinstance(entry, Mapping):
                continue
            fractions = entry.get("fractions")
            if not isinstance(fractions, Mapping):
                continue
            shown = " · ".join(
                f"{hop} {float(frac):.0%}"
                for hop, frac in sorted(
                    fractions.items(), key=lambda kv: -float(kv[1])
                )
                if _finite(frac) is not None and float(frac) >= 0.005
            )
            lines.append(
                f"    {label} {_fmt(_finite(entry.get('latency_ms')), '{:.1f}')} ms: "
                f"{shown} (n={entry.get('n')})"
            )
    return "\n".join(lines)


# --------------------------------------------------------------------------- #
# comparing
# --------------------------------------------------------------------------- #
def compare_runs(
    candidate: Mapping[str, Any],
    baseline: Mapping[str, Any],
    threshold: float = 0.1,
    memory_threshold: Optional[float] = None,
    compile_threshold: Optional[float] = None,
) -> Tuple[List[str], List[str]]:
    """(report lines, regression lines) for candidate vs baseline.

    A regression is a relative drop beyond ``threshold`` in throughput or MFU,
    new retraces, or a LOWER-better metric growing past its own threshold:
    ``peak_memory_bytes`` beyond ``memory_threshold`` (default: ``threshold``)
    and ``compile_seconds`` beyond ``compile_threshold`` (default:
    ``max(threshold, 0.5)`` — compile wall-time is machine-noisy, so the gate
    only catches step-function growth like a new compiled variant). Bench-suite
    rows compare per row name; rows carrying an ``error`` field on either side
    are skipped (the by-design 1M plain-CE OOM row must not trip the gate),
    but a row that errors ONLY in the candidate is a regression. ``prec_*``
    and ``*_remat_*`` rows (the precision-ladder and remat families)
    additionally gate their per-row ``hbm_peak_bytes`` lower-better on
    ``memory_threshold`` — a regression that only moves bytes still fails —
    and a candidate carrying a ``<base>_remat_{off,on}`` pair must show
    remat-on strictly below remat-off on ``hbm_peak_bytes`` (the
    candidate-alone invariant, like the packing gate). Serving ``quant`` blocks
    gate ``recall_at_candidates`` / ``topk_match_rate`` higher-better with an
    absolute 0.005 floor; serving ``ann`` blocks (the IVF rung) gate
    ``recall_at_100`` / ``topk_agreement`` the same way plus ``ann_qps``
    higher-better on the relative threshold. Fleet runs (``bench_fleet.py``)
    gate ``fleet_qps``
    higher-better always, and ``fleet_p99_ms`` / ``fleet_reroute_rate``
    lower-better only when the chaos phase matches on both sides (a kill's
    failover gap and reroutes must not fail against a no-chaos baseline).
    Quality runs (obs.quality) gate ``quality_online_hitrate`` higher-better
    with the same absolute 0.005 floor, and ``quality_drift_psi`` lower-better
    only when the injected-shift phase matches on both sides.
    """
    if memory_threshold is None:
        memory_threshold = threshold
    if compile_threshold is None:
        compile_threshold = max(threshold, 0.5)
    lines: List[str] = [
        f"Compare — candidate {candidate.get('source')} vs baseline {baseline.get('source')}"
    ]
    regressions: List[str] = []

    def check(name: str, cand: Optional[float], base: Optional[float], unit: str = "") -> None:
        if cand is None or base is None:
            lines.append(f"  {name}: candidate={_fmt(cand, '{:.3f}')} baseline={_fmt(base, '{:.3f}')} (not comparable)")
            return
        delta = (cand - base) / base if base else 0.0
        lines.append(
            f"  {name}: {cand:.3f}{unit} vs {base:.3f}{unit} ({delta:+.1%})"
        )
        if base > 0 and cand < base * (1.0 - threshold):
            regressions.append(f"{name} regressed {-delta:.1%} (> {threshold:.0%} threshold)")

    def check_lower_better(
        name: str, cand: Optional[float], base: Optional[float], limit: float, unit: str = ""
    ) -> None:
        if cand is None or base is None:
            lines.append(
                f"  {name}: candidate={_fmt(cand, '{:.3f}')} "
                f"baseline={_fmt(base, '{:.3f}')} (not comparable)"
            )
            return
        delta = (cand - base) / base if base else 0.0
        lines.append(f"  {name}: {cand:.3f}{unit} vs {base:.3f}{unit} ({delta:+.1%})")
        if base > 0 and cand > base * (1.0 + limit):
            regressions.append(
                f"{name} regressed {delta:+.1%} (> {limit:.0%} threshold, lower is better)"
            )

    check("samples_per_sec", candidate.get("samples_per_sec"), baseline.get("samples_per_sec"))
    check("steps_per_sec", candidate.get("steps_per_sec"), baseline.get("steps_per_sec"))
    # end-to-end fit-loop throughput (bench records): the production
    # Trainer.fit(scan_chunk=...) number gates alongside the microbench —
    # but only between runs measured with the SAME chunk/feed variant
    cand_fit = candidate.get("fit_samples_per_sec")
    base_fit = baseline.get("fit_samples_per_sec")
    if cand_fit is not None or base_fit is not None:
        cand_bench = candidate.get("bench") or {}
        base_bench = baseline.get("bench") or {}
        variant_keys = ("fit_scan_chunk", "fit_device_feed")
        if any(cand_bench.get(key) != base_bench.get(key) for key in variant_keys):
            lines.append(
                "  fit_samples_per_sec: variant flags differ "
                f"(candidate {[cand_bench.get(k) for k in variant_keys]} vs "
                f"baseline {[base_bench.get(k) for k in variant_keys]}) — not compared"
            )
        else:
            check("fit_samples_per_sec", cand_fit, base_fit)
    if candidate.get("mfu") is not None and baseline.get("mfu") is not None:
        check("mfu", candidate.get("mfu"), baseline.get("mfu"))
    cand_retraces, base_retraces = candidate.get("retraces"), baseline.get("retraces")
    if cand_retraces is not None and base_retraces is not None:
        lines.append(f"  retraces: {cand_retraces} vs {base_retraces}")
        if cand_retraces > base_retraces:
            regressions.append(
                f"retraces increased {base_retraces} -> {cand_retraces} (shape leak?)"
            )
    # lower-better resource gates: device-memory growth is a capacity
    # regression even at held throughput; compile-time growth is the "one
    # more compiled variant slipped in" signal
    if candidate.get("peak_memory_bytes") is not None or baseline.get("peak_memory_bytes") is not None:
        check_lower_better(
            "peak_memory_bytes",
            _finite(candidate.get("peak_memory_bytes")),
            _finite(baseline.get("peak_memory_bytes")),
            memory_threshold,
        )
    if candidate.get("compile_seconds") is not None or baseline.get("compile_seconds") is not None:
        check_lower_better(
            "compile_seconds",
            _finite(candidate.get("compile_seconds")),
            _finite(baseline.get("compile_seconds")),
            compile_threshold,
            unit="s",
        )
    # bench-suite rows: per-row throughput gates keyed by row name; error
    # rows (the by-design OOM evidence) are reported but never gated — except
    # a NEW error where the baseline measured, which IS the regression
    cand_rows = {
        row.get("row"): row for row in (candidate.get("bench_rows") or []) if row.get("row")
    }
    base_rows = {
        row.get("row"): row for row in (baseline.get("bench_rows") or []) if row.get("row")
    }
    for name in sorted(set(cand_rows) & set(base_rows)):
        cand_row, base_row = cand_rows[name], base_rows[name]
        if base_row.get("error"):
            lines.append(f"  bench_row[{name}]: skipped (baseline error row)")
            continue
        if cand_row.get("error"):
            lines.append(
                f"  bench_row[{name}]: candidate ERROR {cand_row['error']} "
                "(baseline measured)"
            )
            regressions.append(
                f"bench_row[{name}] errored in the candidate but measured in the baseline"
            )
            continue
        check(
            f"bench_row[{name}].samples_per_sec",
            _finite(cand_row.get("samples_per_sec")),
            _finite(base_row.get("samples_per_sec")),
        )
        if (
            _finite(cand_row.get("effective_tokens_per_sec")) is not None
            and _finite(base_row.get("effective_tokens_per_sec")) is not None
        ):
            # the streaming-input rows' REAL-token rate: padding-waste
            # regressions (a packing change that re-inflates the grid) fail
            # here even when samples/sec holds
            check(
                f"bench_row[{name}].effective_tokens_per_sec",
                _finite(cand_row.get("effective_tokens_per_sec")),
                _finite(base_row.get("effective_tokens_per_sec")),
            )
        if name.startswith("prec_") or "_remat_" in name:
            # the precision-ladder and remat rows exist to MOVE bytes: a
            # regression that only grows hbm_peak_bytes (throughput held)
            # must still fail — per-row lower-better on --memory-threshold
            check_lower_better(
                f"bench_row[{name}].hbm_peak_bytes",
                _finite(cand_row.get("hbm_peak_bytes")),
                _finite(base_row.get("hbm_peak_bytes")),
                memory_threshold,
            )
    # sequence-packing invariant, gated on the CANDIDATE alone: when a run
    # carries both the packed and unpacked streaming rows, packed must beat
    # unpacked on effective tokens/s — packing that stops paying for itself
    # is a regression regardless of what the baseline run measured
    unpacked_row = cand_rows.get("stream_parquet") or cand_rows.get("stream_inmem")
    packed_row = cand_rows.get("stream_packed")
    if (
        packed_row is not None
        and unpacked_row is not None
        and not packed_row.get("error")
        and not unpacked_row.get("error")
    ):
        packed_rate = _finite(packed_row.get("effective_tokens_per_sec"))
        unpacked_rate = _finite(unpacked_row.get("effective_tokens_per_sec"))
        if packed_rate is not None and unpacked_rate is not None:
            lines.append(
                "  packing: stream_packed effective tokens/s "
                f"{packed_rate:.0f} vs {unpacked_row.get('row')} {unpacked_rate:.0f}"
            )
            if packed_rate < unpacked_rate:
                regressions.append(
                    "stream_packed effective_tokens_per_sec "
                    f"({packed_rate:.0f}) fell below the unpacked "
                    f"{unpacked_row.get('row')} baseline ({unpacked_rate:.0f})"
                )
    # remat-pair invariant, gated on the CANDIDATE alone: when a run carries
    # a <base>_remat_{off,on} pair, remat-on must carry LOWER hbm_peak_bytes
    # — activation checkpointing that stops moving bytes is a regression
    # regardless of the baseline run (the static memory_analysis claim holds
    # on CPU too, unlike the bf16 byte win)
    for pair_name, pair in (candidate.get("remat_pairs") or {}).items():
        if not isinstance(pair, Mapping):
            continue
        off_hbm = _finite(pair.get("off_hbm_peak_bytes"))
        on_hbm = _finite(pair.get("on_hbm_peak_bytes"))
        if off_hbm is None or on_hbm is None:
            continue
        lines.append(
            f"  remat[{pair_name}]: hbm_peak_bytes on={on_hbm:.0f} "
            f"vs off={off_hbm:.0f}"
        )
        if on_hbm >= off_hbm:
            regressions.append(
                f"remat[{pair_name}] hbm_peak_bytes did not drop "
                f"(on={on_hbm:.0f} >= off={off_hbm:.0f})"
            )
    # anomaly-count gates: a run that skips more steps (or warns more) than
    # its baseline regressed in stability even when throughput held
    for name, label in (
        ("bad_steps", "bad_steps"),
        ("anomalies", "anomalies"),
        ("health_warnings", "health warnings"),
        # lower-better with a zero baseline by design: a healthy run fires no
        # SLO rules, so ANY candidate violation against a clean baseline gates
        ("slo_violations", "SLO violations"),
    ):
        cand_count, base_count = candidate.get(name), baseline.get(name)
        if (
            isinstance(cand_count, int)
            and isinstance(base_count, int)
            and not isinstance(cand_count, bool)
            and not isinstance(base_count, bool)
        ):
            lines.append(f"  {label}: {cand_count} vs {base_count}")
            if cand_count > base_count:
                regressions.append(
                    f"{label} increased {base_count} -> {cand_count} (model-health regression)"
                )
    # promotion rollbacks: lower-better with a zero baseline by design — a
    # healthy continual run rolls nothing back, so ANY candidate rollback
    # against a clean baseline gates (the serve.promote analog of
    # slo_violations)
    cand_rollbacks, base_rollbacks = candidate.get("rollbacks"), baseline.get("rollbacks")
    if (
        isinstance(cand_rollbacks, int)
        and isinstance(base_rollbacks, int)
        and not isinstance(cand_rollbacks, bool)
        and not isinstance(base_rollbacks, bool)
    ):
        lines.append(f"  rollbacks: {cand_rollbacks} vs {base_rollbacks}")
        if cand_rollbacks > base_rollbacks:
            regressions.append(
                f"rollbacks increased {base_rollbacks} -> {cand_rollbacks} "
                "(a candidate generation was auto-rolled back)"
            )
    # serving gates: QPS is higher-better (reuses check); tail latency is
    # LOWER-better — a p99 that grew beyond threshold is a regression even
    # when throughput held (the micro-batcher trading latency for fill is
    # exactly the failure mode this catches)
    # resilience-rate gates, LOWER-better with an absolute floor: rates
    # start at 0.0 in healthy runs, so the relative rule alone (cand >
    # base * (1+t)) would never fire on a 0 -> 0.05 regression — a
    # half-percent absolute rise gates regardless of the baseline
    def check_rate(name: str, cand: Optional[float], base: Optional[float]) -> None:
        if cand is None or base is None:
            lines.append(
                f"  {name}: candidate={_fmt(cand, '{:.4f}')} "
                f"baseline={_fmt(base, '{:.4f}')} (not comparable)"
            )
            return
        lines.append(f"  {name}: {cand:.4f} vs {base:.4f}")
        if cand > base + max(threshold * base, 0.005):
            regressions.append(
                f"{name} regressed {base:.4f} -> {cand:.4f} (lower is better)"
            )

    def surface_rate(name: str, cand: Optional[float], base: Optional[float], why: str) -> None:
        if cand is not None or base is not None:
            lines.append(
                f"  {name}: candidate={_fmt(cand, '{:.4f}')} "
                f"baseline={_fmt(base, '{:.4f}')} (not gated: {why})"
            )

    cand_serve, base_serve = candidate.get("serve") or {}, baseline.get("serve") or {}
    if cand_serve or base_serve:
        check("serve_qps", _finite(cand_serve.get("qps")), _finite(base_serve.get("qps")))
        cand_p99, base_p99 = _finite(cand_serve.get("p99_ms")), _finite(base_serve.get("p99_ms"))
        if cand_p99 is None or base_p99 is None:
            lines.append(
                f"  serve_p99_ms: candidate={_fmt(cand_p99, '{:.3f}')} "
                f"baseline={_fmt(base_p99, '{:.3f}')} (not comparable)"
            )
        else:
            delta = (cand_p99 - base_p99) / base_p99 if base_p99 else 0.0
            lines.append(f"  serve_p99_ms: {cand_p99:.3f} vs {base_p99:.3f} ({delta:+.1%})")
            if base_p99 > 0 and cand_p99 > base_p99 * (1.0 + threshold):
                regressions.append(
                    f"serve_p99_ms regressed {delta:+.1%} (> {threshold:.0%} threshold)"
                )

        # the run-wide rates are dominated by the OPT-IN phases — deadline
        # misses by overload (4x-capacity arrivals against tight deadlines by
        # design), errors by chaos (injected engine faults) — so each gate
        # applies only when the relevant phases match on both sides; a
        # mismatched comparison is surfaced, never gated
        overload_match = bool(cand_serve.get("overload")) == bool(base_serve.get("overload"))
        chaos_match = bool(cand_serve.get("chaos")) == bool(base_serve.get("chaos"))
        cand_err = _finite(cand_serve.get("error_rate"))
        base_err = _finite(base_serve.get("error_rate"))
        if chaos_match:
            check_rate("serve_error_rate", cand_err, base_err)
        else:
            surface_rate(
                "serve_error_rate", cand_err, base_err,
                "chaos phase ran on one side only",
            )
        cand_dm = _finite(cand_serve.get("deadline_miss_rate"))
        base_dm = _finite(base_serve.get("deadline_miss_rate"))
        if overload_match:
            check_rate("serve_deadline_miss_rate", cand_dm, base_dm)
        else:
            surface_rate(
                "serve_deadline_miss_rate", cand_dm, base_dm,
                "overload phase ran on one side only",
            )
        # shed rate only means the same thing between two runs that BOTH ran
        # the overload phase (a no-overload run sheds ~nothing by design) —
        # surfaced always, gated only when comparable
        cand_shed = _finite(cand_serve.get("shed_rate"))
        base_shed = _finite(base_serve.get("shed_rate"))
        if cand_serve.get("overload") and base_serve.get("overload"):
            check_rate("serve_shed_rate", cand_shed, base_shed)
        else:
            surface_rate(
                "serve_shed_rate", cand_shed, base_shed,
                "both sides must run overload mode",
            )
        # swap-under-load tail latency: a hot swap that stalls the worker is
        # exactly what this gate catches — gated lower-better only when BOTH
        # runs ran the swap phase (the PR-9 phase-matching rule), surfaced
        # unGated otherwise
        cand_swap = _finite(cand_serve.get("swap_p99_ms"))
        base_swap = _finite(base_serve.get("swap_p99_ms"))
        if cand_serve.get("swap") and base_serve.get("swap"):
            check_lower_better("swap_p99_ms", cand_swap, base_swap, threshold, unit="ms")
        else:
            surface_rate(
                "swap_p99_ms", cand_swap, base_swap,
                "swap phase ran on one side only",
            )
        for name in ("batch_fill_ratio", "cache_hit_rate"):
            cand_value, base_value = _finite(cand_serve.get(name)), _finite(base_serve.get(name))
            if cand_value is not None and base_value is not None:
                lines.append(f"  serve_{name}: {cand_value:.3f} vs {base_value:.3f}")
        # int8 retrieval quality gates (precision ladder's serving rung):
        # recall@C and the re-ranked top-k agreement are higher-better with an
        # ABSOLUTE floor — retrieval quality sliding within a loose relative
        # threshold is exactly the regression the gate exists to catch, so
        # any drop beyond 0.005 absolute fails
        cand_quant = cand_serve.get("quant") or {}
        base_quant = base_serve.get("quant") or {}
        if cand_quant or base_quant:
            for name in ("recall_at_candidates", "topk_match_rate"):
                cand_value = _finite(cand_quant.get(name))
                base_value = _finite(base_quant.get(name))
                if cand_value is None or base_value is None:
                    lines.append(
                        f"  serve_quant_{name}: candidate={_fmt(cand_value, '{:.4f}')} "
                        f"baseline={_fmt(base_value, '{:.4f}')} (not comparable)"
                    )
                    continue
                lines.append(
                    f"  serve_quant_{name}: {cand_value:.4f} vs {base_value:.4f}"
                )
                if cand_value < base_value - 0.005:
                    regressions.append(
                        f"serve_quant_{name} regressed "
                        f"{base_value:.4f} -> {cand_value:.4f} (higher is better)"
                    )
        # IVF retrieval quality gates (sub-linear serving): same absolute
        # 0.005 floor as the quant rung — approximation quality must not
        # slide; ann_qps gates higher-better on the relative threshold
        cand_ann = cand_serve.get("ann") or {}
        base_ann = base_serve.get("ann") or {}
        if cand_ann or base_ann:
            for name in ("recall_at_100", "topk_agreement"):
                cand_value = _finite(cand_ann.get(name))
                base_value = _finite(base_ann.get(name))
                if cand_value is None or base_value is None:
                    lines.append(
                        f"  serve_ann_{name}: candidate={_fmt(cand_value, '{:.4f}')} "
                        f"baseline={_fmt(base_value, '{:.4f}')} (not comparable)"
                    )
                    continue
                lines.append(
                    f"  serve_ann_{name}: {cand_value:.4f} vs {base_value:.4f}"
                )
                if cand_value < base_value - 0.005:
                    regressions.append(
                        f"serve_ann_{name} regressed "
                        f"{base_value:.4f} -> {cand_value:.4f} (higher is better)"
                    )
            check(
                "serve_ann_qps",
                _finite(cand_ann.get("ivf_qps")),
                _finite(base_ann.get("ivf_qps")),
            )
    # fleet gates (serve.fleet / bench_fleet.py): aggregate QPS is higher-
    # better; tail latency and the reroute rate are LOWER-better — but a
    # chaos run's p99 includes the failover gap and its reroutes are the
    # injected kill's whole point, so both gate only when the chaos phase
    # matches on both sides (the PR-9 phase-matching rule). Cache-hit
    # locality is surfaced — its gate is the candidate-alone acceptance
    # check bench_fleet/CI applies, not a cross-run comparison.
    cand_fleet, base_fleet = candidate.get("fleet") or {}, baseline.get("fleet") or {}
    if cand_fleet or base_fleet:
        check(
            "fleet_qps", _finite(cand_fleet.get("qps")), _finite(base_fleet.get("qps"))
        )
        fleet_chaos_match = bool(cand_fleet.get("chaos")) == bool(base_fleet.get("chaos"))
        cand_p99 = _finite(cand_fleet.get("p99_ms"))
        base_p99 = _finite(base_fleet.get("p99_ms"))
        if fleet_chaos_match:
            check_lower_better("fleet_p99_ms", cand_p99, base_p99, threshold, unit="ms")
        else:
            surface_rate(
                "fleet_p99_ms", cand_p99, base_p99,
                "chaos phase ran on one side only",
            )
        cand_reroute = _finite(cand_fleet.get("reroute_rate"))
        base_reroute = _finite(base_fleet.get("reroute_rate"))
        if fleet_chaos_match:
            check_rate("fleet_reroute_rate", cand_reroute, base_reroute)
        else:
            surface_rate(
                "fleet_reroute_rate", cand_reroute, base_reroute,
                "chaos phase ran on one side only",
            )
        cand_loc = _finite(cand_fleet.get("cache_hit_locality"))
        base_loc = _finite(base_fleet.get("cache_hit_locality"))
        if cand_loc is not None and base_loc is not None:
            lines.append(f"  fleet_cache_hit_locality: {cand_loc:.3f} vs {base_loc:.3f}")
    # quality gates (obs.quality): the ONLINE prequential hitrate is higher-
    # better with an ABSOLUTE floor (same rule as the quant recall gates —
    # online ranking quality sliding within a loose relative threshold is
    # exactly what this gate exists to catch); drift PSI is lower-better but
    # only between two runs that BOTH ran the injected-shift phase (the
    # phase-matching rule: a drift run's psi peak is the injection's whole
    # point and must not fail against a steady-traffic baseline)
    cand_quality = candidate.get("quality") or {}
    base_quality = baseline.get("quality") or {}
    if cand_quality or base_quality:
        cand_hr = _finite(cand_quality.get("online_hitrate_cum"))
        base_hr = _finite(base_quality.get("online_hitrate_cum"))
        if cand_hr is None or base_hr is None:
            lines.append(
                f"  quality_online_hitrate: candidate={_fmt(cand_hr, '{:.4f}')} "
                f"baseline={_fmt(base_hr, '{:.4f}')} (not comparable)"
            )
        else:
            lines.append(
                f"  quality_online_hitrate: {cand_hr:.4f} vs {base_hr:.4f}"
            )
            if cand_hr < base_hr - 0.005:
                regressions.append(
                    f"quality_online_hitrate regressed "
                    f"{base_hr:.4f} -> {cand_hr:.4f} (higher is better)"
                )
        cand_ndcg = _finite(cand_quality.get("online_ndcg_cum"))
        base_ndcg = _finite(base_quality.get("online_ndcg_cum"))
        if cand_ndcg is not None and base_ndcg is not None:
            lines.append(
                f"  quality_online_ndcg: {cand_ndcg:.4f} vs {base_ndcg:.4f}"
            )
        cand_psi = _finite(cand_quality.get("drift_psi_peak"))
        base_psi = _finite(base_quality.get("drift_psi_peak"))
        if cand_quality.get("drift_phase") and base_quality.get("drift_phase"):
            check_lower_better("quality_drift_psi", cand_psi, base_psi, threshold)
        else:
            surface_rate(
                "quality_drift_psi", cand_psi, base_psi,
                "drift phase ran on one side only",
            )
    # tail-attribution gate: a hop's SHARE of the p99 mix growing by more
    # than 10 points is a regression even when p99 itself is flat — where
    # the tail's time goes is its own contract (e.g. queue_wait swallowing
    # the mix says batching went wrong before latency SLOs notice). Absolute
    # point shift, not relative: a 2%→4% hop doubling is noise, 30%→42%
    # is not. Chaos-phase-matched like the fleet latency gates; smaller
    # shifts (≥ 2 points) are surfaced without gating.
    cand_attr = candidate.get("tail_attribution") or {}
    base_attr = baseline.get("tail_attribution") or {}
    cand_p99_mix = ((cand_attr.get("quantiles") or {}).get("p99") or {}).get("fractions")
    base_p99_mix = ((base_attr.get("quantiles") or {}).get("p99") or {}).get("fractions")
    if isinstance(cand_p99_mix, Mapping) and isinstance(base_p99_mix, Mapping):
        attr_chaos_match = bool((candidate.get("fleet") or {}).get("chaos")) == bool(
            (baseline.get("fleet") or {}).get("chaos")
        )
        for name in sorted(set(cand_p99_mix) | set(base_p99_mix)):
            cand_frac = _finite(cand_p99_mix.get(name))
            base_frac = _finite(base_p99_mix.get(name))
            if cand_frac is None or base_frac is None:
                continue
            shift = cand_frac - base_frac
            if abs(shift) >= 0.02:
                lines.append(
                    f"  tail_p99_share/{name}: {cand_frac:.1%} vs {base_frac:.1%}"
                )
            if shift > 0.10:
                if attr_chaos_match:
                    regressions.append(
                        f"tail_p99_share/{name} grew {base_frac:.1%} -> "
                        f"{cand_frac:.1%} (> 10-point shift in the p99 hop mix)"
                    )
                else:
                    lines.append(
                        f"  tail_p99_share/{name}: not gated "
                        "(chaos phase ran on one side only)"
                    )
    # cross-host balance: the straggler index (max/median per-host step time)
    # gates lower-better, but ONLY between two genuinely multi-process runs —
    # a single-process run's index is 1.0 by construction and comparing it
    # against a real fleet would read as a free pass (or a fake regression)
    cand_procs = candidate.get("processes") or {}
    base_procs = baseline.get("processes") or {}
    cand_multi = (cand_procs.get("count") or 0) > 1
    base_multi = (base_procs.get("count") or 0) > 1
    cand_straggler = _finite(cand_procs.get("straggler_index"))
    base_straggler = _finite(base_procs.get("straggler_index"))
    if cand_multi and base_multi:
        check_lower_better(
            "straggler_index", cand_straggler, base_straggler, threshold
        )
    elif cand_straggler is not None or base_straggler is not None:
        lines.append(
            f"  straggler_index: candidate={_fmt(cand_straggler, '{:.3f}')} "
            f"baseline={_fmt(base_straggler, '{:.3f}')} "
            "(not gated: both runs must be multi-process)"
        )
    cand_gp, base_gp = candidate.get("goodput"), baseline.get("goodput")
    if cand_gp and base_gp:
        for name in (
            *GOODPUT_SPANS,
            *(n for n in SERVE_GOODPUT_SPANS if n not in GOODPUT_SPANS),
            "other",
        ):
            cand_frac = float((cand_gp.get("fractions") or {}).get(name, 0.0))
            base_frac = float((base_gp.get("fractions") or {}).get(name, 0.0))
            if abs(cand_frac - base_frac) >= 0.01:
                lines.append(
                    f"  goodput/{name}: {cand_frac:.1%} vs {base_frac:.1%}"
                )
    return lines, regressions


# --------------------------------------------------------------------------- #
# CLI
# --------------------------------------------------------------------------- #
def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m replay_tpu.obs.report",
        description="Summarize a run's events.jsonl (+ trace.json) into a run report.",
    )
    parser.add_argument(
        "run", help="run directory, events.jsonl path, or single-record bench JSON"
    )
    parser.add_argument(
        "--compare",
        metavar="RUN",
        help="baseline run (same formats); exits non-zero on regression",
    )
    parser.add_argument(
        "--threshold",
        type=float,
        default=0.1,
        help="relative regression threshold for --compare (default 0.1 = 10%%)",
    )
    parser.add_argument(
        "--memory-threshold",
        type=float,
        default=None,
        help="relative growth threshold for peak_memory_bytes (lower-better "
        "gate; default: --threshold)",
    )
    parser.add_argument(
        "--compile-threshold",
        type=float,
        default=None,
        help="relative growth threshold for compile_seconds (lower-better "
        "gate; default: max(--threshold, 0.5) — compile time is machine-noisy)",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit the summary as JSON instead of text"
    )
    parser.add_argument(
        "--postmortem",
        action="store_true",
        help="reconstruct per-process last-known-activity timelines from "
        "flight rings + event shards + worker meta + checkpoint sidecars "
        "(obs.postmortem); writes <run_dir>/postmortem.json. Torn rings and "
        "damaged shards are reported, never fatal.",
    )
    args = parser.parse_args(argv)

    if args.postmortem:
        from .postmortem import build_postmortem, render_postmortem

        try:
            post = build_postmortem(args.run)
        except (OSError, ValueError) as exc:
            print(f"report: cannot post-mortem {args.run}: {exc}", file=sys.stderr)
            return 1
        out_path = os.path.join(args.run, "postmortem.json")
        with open(out_path, "w") as fh:
            json.dump(post, fh, indent=2, default=str)
            fh.write("\n")
        if args.json:
            print(json.dumps(post, indent=2, default=str))
        else:
            print(render_postmortem(post))
            print(f"  written: {out_path}")
        return 0

    try:
        summary = summarize_run(args.run)
    except (OSError, ValueError) as exc:
        print(f"report: cannot parse {args.run}: {exc}", file=sys.stderr)
        return 1

    if args.json:
        print(json.dumps(summary, indent=2, allow_nan=False, default=str))
    else:
        print(render(summary))

    if args.compare:
        try:
            baseline = summarize_run(args.compare)
        except (OSError, ValueError) as exc:
            print(f"report: cannot parse {args.compare}: {exc}", file=sys.stderr)
            return 1
        lines, regressions = compare_runs(
            summary,
            baseline,
            threshold=args.threshold,
            memory_threshold=args.memory_threshold,
            compile_threshold=args.compile_threshold,
        )
        print()
        print("\n".join(lines))
        if regressions:
            for regression in regressions:
                print(f"REGRESSION: {regression}", file=sys.stderr)
            return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
