"""Static roofline analysis of compiled programs: memory- vs compute-bound.

MFU alone lies about fused/memory-bound programs: a head that is hard against
the HBM bandwidth wall can never reach the MXU peak, so "6.9% MFU" reads as
failure when it may be 90% of what the chip can physically deliver for that
program. The roofline model (flops ÷ bytes = arithmetic intensity, ceiling =
min(peak FLOPs, intensity × peak bandwidth)) turns the same two cost-model
numbers into the *honest* target: "achieved X% of the roofline-predicted
ceiling". PR 7's memory-wall fix was diagnosed by hand from exactly this
arithmetic in a doc (BENCH_NOTES.md); this module makes the framework do it
for every compiled program — per-step fit, scan chunk, CompiledInference
buckets, the CEFused/CEFusedTP heads — from XLA's own ``cost_analysis()``
(flops, bytes accessed) and ``memory_analysis()`` (argument/output/temp
bytes), no execution required.

Import-light like :mod:`.mfu` (jax only inside :func:`analyze_program`):
drivers consult the peak tables before deciding whether jax may be imported.
The bandwidth table mirrors :data:`.mfu.PEAK_BF16_TFLOPS`; on hosts without a
table entry (CPU CI), ``REPLAY_TPU_ROOFLINE_ASSUME_KIND`` (or the existing
``REPLAY_TPU_BENCH_ASSUME_KIND``) classifies against an assumed chip and the
record carries ``peak_assumed`` so arithmetic can never read as measurement.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Mapping, Optional

from .mfu import peak_tflops, program_costs

__all__ = [
    "PEAK_HBM_GBPS",
    "analyze_costs",
    "analyze_program",
    "assumed_device_kind",
    "bench_fields",
    "classify",
    "of_ceiling",
    "peak_bandwidth",
]

# peak HBM bandwidth in GB/s per chip, keyed like mfu.PEAK_BF16_TFLOPS
# (substring of jax Device.device_kind)
PEAK_HBM_GBPS = {
    "v5 lite": 819.0,
    "v5e": 819.0,
    "v5p": 2765.0,
    "v6 lite": 1640.0,
    "v6e": 1640.0,
    "v4": 1228.0,
    "v3": 900.0,
    "v2": 700.0,
}


def peak_bandwidth(device_kind: str) -> Optional[float]:
    """Peak HBM GB/s for a ``jax.Device.device_kind`` string, or None for
    kinds without a table entry (CPU hosts, unknown chips)."""
    kind = (device_kind or "").lower()
    for key, peak in PEAK_HBM_GBPS.items():
        if key in kind:
            return peak
    return None


def assumed_device_kind() -> Optional[str]:
    """The chip kind CPU-smoke runs classify against (arithmetic, not
    measurement): ``REPLAY_TPU_ROOFLINE_ASSUME_KIND``, falling back to the
    bench suite's existing ``REPLAY_TPU_BENCH_ASSUME_KIND``."""
    return os.environ.get("REPLAY_TPU_ROOFLINE_ASSUME_KIND") or os.environ.get(
        "REPLAY_TPU_BENCH_ASSUME_KIND"
    )


def classify(
    flops: float,
    bytes_accessed: float,
    device_kind: str,
    allow_assumed: bool = True,
) -> Optional[Dict[str, Any]]:
    """Roofline classification of one program against one chip's peaks.

    ``critical_intensity`` (flops/byte) is where the roofline's slanted and
    flat parts meet: a program below it is ``"memory"``-bound (its ceiling is
    ``intensity × bandwidth``), above it ``"compute"``-bound (ceiling = MXU
    peak). Returns None when neither the real ``device_kind`` nor an assumed
    kind has table entries, or the cost-model inputs are degenerate — an
    unclassifiable program must stay visibly unclassified, not default to a
    bound.
    """
    flops = float(flops or 0.0)
    bytes_accessed = float(bytes_accessed or 0.0)
    if flops <= 0.0 or bytes_accessed <= 0.0:
        return None
    peak_flops = peak_tflops(device_kind)
    peak_gbps = peak_bandwidth(device_kind)
    assumed = None
    if (peak_flops is None or peak_gbps is None) and allow_assumed:
        assumed = assumed_device_kind()
        if assumed:
            peak_flops = peak_tflops(assumed)
            peak_gbps = peak_bandwidth(assumed)
    if not peak_flops or not peak_gbps:
        return None
    intensity = flops / bytes_accessed
    critical = (peak_flops * 1e12) / (peak_gbps * 1e9)
    bandwidth_ceiling_tflops = intensity * peak_gbps * 1e9 / 1e12
    ceiling = min(peak_flops, bandwidth_ceiling_tflops)
    record = {
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "arithmetic_intensity": intensity,
        "critical_intensity": critical,
        "bound": "memory" if intensity < critical else "compute",
        "ceiling_tflops": ceiling,
        "peak_tflops": peak_flops,
        "peak_hbm_gbps": peak_gbps,
        # the bandwidth-side step-time floor: bytes / peak bandwidth (the
        # compute-side floor is flops / peak flops; the max binds)
        "min_step_seconds": max(
            bytes_accessed / (peak_gbps * 1e9), flops / (peak_flops * 1e12)
        ),
    }
    if assumed:
        record["peak_assumed"] = assumed
    return record


def bench_fields(
    static_record: Optional[Mapping[str, Any]],
    tflops_per_sec: Optional[float] = None,
    device_count: int = 1,
) -> Dict[str, Any]:
    """The flat bench-record fields derived from an :func:`analyze_program`
    record — ONE shaping of key names/rounding shared by ``bench.py`` and
    every ``bench_suite.py`` row, so the two harnesses cannot drift:
    ``hbm_peak_bytes``, ``collective_bytes``, ``roofline_bound``,
    ``roofline_ceiling_tflops``, ``arithmetic_intensity``,
    ``roofline_peak_assumed`` and — when the achieved rate is known —
    ``of_roofline_ceiling`` (per chip, like the ceiling tables)."""
    fields: Dict[str, Any] = {}
    if static_record is None:
        return fields
    if static_record.get("hbm_peak_bytes") is not None:
        fields["hbm_peak_bytes"] = static_record["hbm_peak_bytes"]
    if static_record.get("collective_bytes") is not None:
        fields["collective_bytes"] = static_record["collective_bytes"]
    classification = static_record.get("roofline")
    if classification:
        fields["roofline_bound"] = classification["bound"]
        fields["roofline_ceiling_tflops"] = round(classification["ceiling_tflops"], 3)
        fields["arithmetic_intensity"] = round(classification["arithmetic_intensity"], 2)
        if classification.get("peak_assumed"):
            fields["roofline_peak_assumed"] = classification["peak_assumed"]
        if tflops_per_sec is not None and classification.get("ceiling_tflops"):
            fields["of_roofline_ceiling"] = round(
                float(tflops_per_sec)
                / max(int(device_count), 1)
                / classification["ceiling_tflops"],
                4,
            )
    return fields


def of_ceiling(tflops_per_sec: Optional[float], record: Optional[Mapping[str, Any]]) -> Optional[float]:
    """Achieved ÷ roofline-predicted ceiling — the honest MFU for programs
    whose ceiling is the bandwidth roof, not the MXU peak."""
    if record is None or tflops_per_sec is None:
        return None
    ceiling = record.get("ceiling_tflops")
    if not ceiling:
        return None
    return float(tflops_per_sec) / float(ceiling)


def analyze_program(
    jitted_fn: Any,
    *args,
    device_kind: Optional[str] = None,
    extra_flops: float = 0.0,
    extra_bytes: float = 0.0,
    mesh_shape: Optional[Mapping[str, int]] = None,
    **kwargs,
) -> Optional[Dict[str, Any]]:
    """The full static record for one compiled program: roofline + memory +
    collectives — one ``lower().compile()``, no execution.

    ``extra_flops`` / ``extra_bytes`` add work opaque to the XLA cost model
    (pallas custom calls: the CEFused head's analytic FLOPs via
    :func:`.mfu.fused_ce_flops`, and its ``rows×items`` logits traffic that
    the kernel keeps OUT of HBM — pass the bytes it actually touches, i.e.
    the table + hidden sweeps). Returns None when the backend offers no
    analysis; partial records (memory without a roofline) degrade per-field.

    The record::

        {"roofline": classify(...) | None,
         "hbm_peak_bytes", "argument_bytes", "output_bytes", "temp_bytes",
         "collectives": {"count", "bytes", "by_op"},
         "collective_bytes"}
    """
    costs = program_costs(jitted_fn, *args, **kwargs)
    return analyze_costs(
        costs,
        device_kind=device_kind,
        extra_flops=extra_flops,
        extra_bytes=extra_bytes,
        mesh_shape=mesh_shape,
    )


def analyze_costs(
    costs: Optional[Mapping[str, Any]],
    device_kind: Optional[str] = None,
    extra_flops: float = 0.0,
    extra_bytes: float = 0.0,
    mesh_shape: Optional[Mapping[str, int]] = None,
) -> Optional[Dict[str, Any]]:
    """:func:`analyze_program` on an already-extracted
    :func:`.mfu.program_costs` / :func:`.mfu.compiled_costs` record — lets a
    caller reuse ONE compile for both the roofline and the device-time
    attribution's HLO text."""
    if costs is None:
        return None
    if device_kind is None:
        try:
            import jax

            device_kind = jax.devices()[0].device_kind
        except Exception:
            device_kind = ""
    flops = (costs.get("flops") or 0.0) + float(extra_flops)
    bytes_accessed = (costs.get("bytes_accessed") or 0.0) + float(extra_bytes)
    record: Dict[str, Any] = {
        "roofline": classify(flops, bytes_accessed, device_kind or ""),
        "flops": flops,
        "bytes_accessed": bytes_accessed,
    }
    memory = costs.get("memory") or {}
    for key in (
        "argument_bytes", "output_bytes", "temp_bytes", "generated_code_bytes",
        "alias_bytes",
    ):
        if key in memory:
            record[key] = memory[key]
    if memory:
        # the static peak estimate: everything the executable holds resident
        # at once (arguments + outputs + scratch + code). Donated/aliased
        # buffers appear in BOTH argument and output totals with the overlap
        # reported as alias bytes — subtract it or the donated train state
        # (params + optimizer moments, the bulk of a fit's footprint) counts
        # twice.
        record["hbm_peak_bytes"] = max(
            int(
                (memory.get("argument_bytes") or 0)
                + (memory.get("output_bytes") or 0)
                + (memory.get("temp_bytes") or 0)
                + (memory.get("generated_code_bytes") or 0)
                - (memory.get("alias_bytes") or 0)
            ),
            0,
        )
    hlo_text = costs.get("hlo_text")
    if hlo_text:
        from replay_tpu.parallel.introspect import (
            collective_inventory,
            summarize_collectives,
        )

        inventory = collective_inventory(hlo_text, mesh_shape=mesh_shape)
        record["collectives"] = summarize_collectives(inventory)
        record["collective_bytes"] = record["collectives"]["bytes"]
    return record
