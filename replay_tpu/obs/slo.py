"""Declarative SLO watchdogs over the live metrics registry.

The threshold-gated half of the live observability plane: operators declare
rules over metric names (:mod:`replay_tpu.obs.metrics`), the watchdog
evaluates them at step/batch cadence (the :class:`~replay_tpu.obs.metrics.
MetricsLogger` calls :meth:`SLOWatchdog.evaluate` after every bridged
``on_train_step`` / ``on_serve_batch``), and breaches flow as
``on_slo_violation`` events through the SAME sinks every other event uses —
so a violation lands in ``events.jsonl``, prints on the console
(:class:`~replay_tpu.obs.events.ConsoleLogger`'s warning-class render), counts
in the registry (``replay_slo_violations_total``) and gates ``obs.report
--compare`` (lower-better, 0 → any fires).

Breach→recovery state machine (per rule)::

    ok ──condition holds──▶ breaching (counts consecutive evaluations)
    breaching ──held for `for_steps` evals──▶ VIOLATION (one on_slo_violation)
    violation ──condition clears──▶ ok       (one on_slo_recovery, with the
                                              breach duration + eval count)

Firing on the *transition* (not per evaluation) is what makes "a NaN step
trips the bad_steps rule exactly once" testable, and the recovery event's
``breach_seconds`` is what distinguishes a transient spike from a sustained
breach in the report. The clock is injectable for deterministic tests.

Stdlib-only, like the rest of the live plane.
"""

from __future__ import annotations

import operator
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

from .events import TrainerEvent
from .metrics import MetricsRegistry

__all__ = ["SLORule", "SLOWatchdog"]

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": operator.gt,
    ">=": operator.ge,
    "<": operator.lt,
    "<=": operator.le,
    "==": operator.eq,
    "!=": operator.ne,
}


@dataclass(frozen=True)
class SLORule:
    """One declarative threshold over a registry metric.

    :param metric: registry name, with the ``:stat`` suffix for histograms
        (``replay_serve_queue_wait_ms:p99``, ``replay_train_step_seconds:mean``
        — see :meth:`~replay_tpu.obs.metrics.MetricsRegistry.value`).
    :param op: comparison the *breach* satisfies — ``"replay_train_bad_steps"
        > 0`` breaches when bad steps appear.
    :param threshold: the boundary value.
    :param for_steps: consecutive evaluations the condition must hold before
        the violation fires (1 = immediately). Debounces flappy metrics:
        ``for_steps=5`` on a p99 gauge means five consecutive steps over
        budget, not one unlucky scrape.
    :param labels: label set selecting one series of a labeled metric —
        required for metrics that only exist labeled
        (``replay_serve_degraded_total`` is per ``to=``,
        ``replay_goodput_fraction`` per ``phase=``, ``replay_serve_lane_depth``
        per ``lane=``); the unlabeled read of such a metric is permanent
        "no data" and the rule would never evaluate.
    :param name: label for events/metrics; defaults to
        ``"<metric>{k=v}<op><threshold>"``.
    """

    metric: str
    op: str
    threshold: float
    for_steps: int = 1
    labels: Optional[Mapping[str, str]] = None
    name: Optional[str] = None

    def __post_init__(self) -> None:
        if self.op not in _OPS:
            msg = f"unknown op {self.op!r}; use one of {sorted(_OPS)}"
            raise ValueError(msg)
        if self.for_steps < 1:
            msg = "for_steps must be >= 1 (consecutive breaching evaluations)"
            raise ValueError(msg)

    @property
    def label(self) -> str:
        if self.name:
            return self.name
        rendered = (
            "{" + ",".join(f"{k}={v}" for k, v in sorted(self.labels.items())) + "}"
            if self.labels
            else ""
        )
        return f"{self.metric}{rendered}{self.op}{self.threshold:g}"

    def breached(self, value: float) -> bool:
        return _OPS[self.op](value, self.threshold)


@dataclass
class _RuleState:
    consecutive: int = 0
    active: bool = False
    breach_started: Optional[float] = None
    fired: int = 0


class SLOWatchdog:
    """Evaluate a rule set against a registry; emit transition events.

    ``emit`` receives :class:`TrainerEvent` records — wire it to the run's
    sink fan-out (``Trainer.fit`` points it at the same ``MultiLogger`` every
    other event flows through). A metric that does not exist yet is treated
    as "no data": the rule's state is untouched (a rule on a serve gauge must
    not flap while only training events have arrived).

    Thread-light: evaluations are serialized by the caller (the bridge calls
    from whatever thread delivered the event, but one event at a time per
    sink fan-out); state transitions are simple python so a rare concurrent
    pair of evaluations cannot corrupt more than one consecutive-count.
    """

    def __init__(
        self,
        rules: Sequence[SLORule],
        registry: MetricsRegistry,
        emit: Optional[Callable[[TrainerEvent], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.rules = tuple(rules)
        labels = [rule.label for rule in self.rules]
        if len(set(labels)) != len(labels):
            msg = f"duplicate SLO rule labels: {sorted(labels)}"
            raise ValueError(msg)
        self.registry = registry
        self.emit = emit
        self.clock = clock
        self._state: Dict[str, _RuleState] = {rule.label: _RuleState() for rule in self.rules}

    # -- introspection ------------------------------------------------------ #
    @property
    def active(self) -> List[str]:
        """Labels of rules currently in violation."""
        return [label for label, state in self._state.items() if state.active]

    def stats(self) -> Dict[str, Mapping[str, Any]]:
        return {
            label: {
                "active": state.active,
                "consecutive": state.consecutive,
                "fired": state.fired,
            }
            for label, state in self._state.items()
        }

    # -- evaluation --------------------------------------------------------- #
    def _send(self, event: TrainerEvent) -> None:
        if self.emit is not None:
            self.emit(event)

    def evaluate(self, step: Optional[int] = None) -> List[TrainerEvent]:
        """One pass over every rule; returns the transition events emitted."""
        now = self.clock()
        emitted: List[TrainerEvent] = []
        for rule in self.rules:
            state = self._state[rule.label]
            value = self.registry.value(rule.metric, labels=rule.labels)
            if value is None:
                continue  # no data yet: neither a breach nor a recovery
            if rule.breached(value):
                state.consecutive += 1
                if state.breach_started is None:
                    state.breach_started = now
                if not state.active and state.consecutive >= rule.for_steps:
                    state.active = True
                    state.fired += 1
                    self.registry.set(
                        "replay_slo_breached", 1.0, labels={"rule": rule.label}
                    )
                    event = TrainerEvent(
                        event="on_slo_violation",
                        step=step,
                        payload={
                            "rule": rule.label,
                            "metric": rule.metric,
                            "op": rule.op,
                            "threshold": rule.threshold,
                            "value": value,
                            "consecutive": state.consecutive,
                        },
                    )
                    emitted.append(event)
                    self._send(event)
            else:
                if state.active:
                    breach_seconds = (
                        now - state.breach_started
                        if state.breach_started is not None
                        else 0.0
                    )
                    self.registry.set(
                        "replay_slo_breached", 0.0, labels={"rule": rule.label}
                    )
                    event = TrainerEvent(
                        event="on_slo_recovery",
                        step=step,
                        payload={
                            "rule": rule.label,
                            "metric": rule.metric,
                            "value": value,
                            "breach_seconds": breach_seconds,
                            "breached_evaluations": state.consecutive,
                        },
                    )
                    emitted.append(event)
                    self._send(event)
                state.active = False
                state.consecutive = 0
                state.breach_started = None
        return emitted
