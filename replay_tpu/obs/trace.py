"""Host-side span tracing + goodput accounting (the profiler/Timer replacement).

Parity target: PyTorch Lightning meters a run with its profiler connector and
``Timer`` callback (replay's Lightning stack gets both for free); this layer
does the same job for the JAX trainer and goes further — it answers the
question Lightning never could: *where does wall-clock go between optimizer
steps?* TurboGR-style goodput accounting (PAPERS.md) splits a run into
``data_wait`` / ``h2d`` / ``compile`` / ``train_step`` / ``validation`` /
``checkpoint`` / ``recovery`` phases whose fractions sum to 1.0, so "is the
TPU idle because of the host?" is a one-line answer.

Design:

* :class:`Tracer` records nestable spans via ``with tracer.span(name):``.
  Thread-safe (per-thread nesting stacks, one lock on the event list) so the
  prefetch thread's ``batch_build`` spans coexist with the fit loop's spans.
  Disabled tracers return a shared null context — near-zero overhead, safe to
  leave the instrumentation in hot paths.
* Exports Chrome trace-event JSON (:meth:`Tracer.save` → ``trace.json``),
  loadable in Perfetto / ``chrome://tracing`` next to a ``jax.profiler``
  device trace; wrap device-side blocks in ``jax.named_scope`` so the two
  correlate by name.
* :meth:`Tracer.summary` aggregates per-name **inclusive** and **exclusive**
  (self) time; :func:`goodput_breakdown` turns an exclusive-time snapshot
  diff into the epoch/fit goodput record carried by ``on_epoch_end`` /
  ``on_fit_end`` events.

The module is import-light on purpose (no jax, no numpy): the report CLI and
the core-tier tests run it host-only.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
from typing import Any, Dict, Iterable, Iterator, List, Mapping, Optional, Sequence, Tuple

__all__ = [
    "GOODPUT_SPANS",
    "REQUEST_HOP_SPANS",
    "SERVE_GOODPUT_SPANS",
    "TraceContext",
    "Tracer",
    "goodput_breakdown",
    "lifecycle_span",
    "merge_traces",
    "tail_attribution",
    "traced_iterator",
]

# the phases of the goodput breakdown, in display order. "other" (derived) is
# everything the instrumentation did not attribute: python loop overhead,
# event emission, metric host work between steps. "batch_build" is the
# batcher's assembly work (SequenceBatcher(tracer=...)): when the batcher runs
# on the consuming thread its spans nest inside data_wait — listing it here
# keeps that time counted as input time rather than leaking into "other".
# "h2d" covers device placement; under fit(scan_chunk=...) the device feed
# records it on the FEEDER thread, so it appears in trace.json but drops out
# of the fit thread's fractions — the drop is the overlap the feed bought
# (obs.report renders the across-thread total next to the in-loop share).
GOODPUT_SPANS = (
    "data_wait",
    "batch_build",
    "h2d",
    "compile",
    "train_step",
    "validation",
    "checkpoint",
    "recovery",
)

# the serving pipeline's phases (replay_tpu.serve): a request waits in the
# micro-batcher queue ("queue_wait", recorded cross-thread via
# :func:`lifecycle_span`), its batch is assembled ("batch_build", shared with
# the training batcher), scored on device ("score"), and — on the fused
# candidate->rank path — retrieved ("retrieve") and re-ranked ("rerank").
# ``goodput_breakdown(..., spans=SERVE_GOODPUT_SPANS)`` folds a serve worker's
# wall clock into fractions summing to 1.0, same contract as training.
SERVE_GOODPUT_SPANS = (
    "queue_wait",
    "batch_build",
    "score",
    "retrieve",
    "rerank",
)

# one fleet request's hops, in timeline order: the router's hash lookup
# ("route"), its hedge/backoff waits, then the replica-side serving phases.
# These are the rows of the report's "tail attribution" section — every span
# recorded with a ``trace_id`` (or batch-level ``trace_ids``) arg under one of
# these names is attributed to that request; the residual inside the
# root "request" span is "other" (dispatch handoffs, future resolution,
# device-queue time the host spans do not cover).
REQUEST_HOP_SPANS = (
    "route",
    "queue_wait",
    "batch_build",
    "score",
    "retrieve",
    "rerank",
    "backoff_wait",
    "hedge_wait",
)

# the spans that make up the stepping pipeline: the denominator of the
# input-starvation metric (time the step loop spent waiting on the batcher
# as a fraction of the loop's total productive+waiting time)
_STEP_PIPELINE = ("data_wait", "batch_build", "h2d", "compile", "train_step")

# the numerator: total input-side wait (blocking on the iterator + the batch
# assembly that happened inside that wait)
_INPUT_SPANS = ("data_wait", "batch_build")

_NULL_CONTEXT = contextlib.nullcontext()

# trace ids are minted per process: a short random-ish prefix (pid + coarse
# wall clock, fixed at import) plus a monotone sequence — unique across the
# fleet's processes without any coordination, and cheap (no uuid4 per request)
_TRACE_SEQ = itertools.count(1)
_TRACE_PREFIX = f"{os.getpid():x}{int(time.time() * 1e3) & 0xFFFFFF:06x}"


class TraceContext:
    """One request's distributed-trace identity: ``trace_id`` + parent span.

    Deliberately pure-JSON (:meth:`to_json` / :meth:`from_json` round-trip a
    plain dict of strings) so the context survives a future socket boundary
    between router and replica processes (ROADMAP item 9) unchanged — today it
    rides in-process through ``ScoringService.submit(_trace=...)``. Minted at
    fleet admission (:meth:`mint`); every hop records its span with
    ``trace_id=...`` in the span args, which is what lets
    :func:`merge_traces` + Perfetto render one hedged-and-failed-over request
    as a single connected timeline across router and replica tracks, and what
    :func:`tail_attribution` groups by.

    Tracing off = no context: the fleet mints only when its tracer is
    enabled, so the disabled hot path allocates nothing (``trace is None``
    everywhere).
    """

    __slots__ = ("trace_id", "parent_span")

    def __init__(self, trace_id: str, parent_span: Optional[str] = None) -> None:
        self.trace_id = str(trace_id)
        self.parent_span = parent_span

    @classmethod
    def mint(cls) -> "TraceContext":
        """A fresh root context (no parent span) — fleet admission."""
        return cls(f"t-{_TRACE_PREFIX}-{next(_TRACE_SEQ):06x}")

    def child(self, parent_span: str) -> "TraceContext":
        """The same trace, one hop deeper (``parent_span`` names the hop that
        forwarded it — e.g. ``"route"`` on the replica-bound context)."""
        return TraceContext(self.trace_id, parent_span=str(parent_span))

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"trace_id": self.trace_id}
        if self.parent_span is not None:
            out["parent_span"] = self.parent_span
        return out

    @classmethod
    def from_json(cls, payload: Optional[Mapping[str, Any]]) -> Optional["TraceContext"]:
        if not payload or "trace_id" not in payload:
            return None
        return cls(payload["trace_id"], parent_span=payload.get("parent_span"))

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"TraceContext({self.trace_id!r}, parent_span={self.parent_span!r})"


class _Span:
    """One live span: a reusable-context-manager-shaped frame.

    Returned by :meth:`Tracer.span`; keeps a reference to its recorded event
    dict after exit so :meth:`Tracer.carve` can re-attribute part of its self
    time (the compile-inside-first-step case).
    """

    __slots__ = ("_tracer", "name", "args", "start", "child_seconds", "record")

    def __init__(self, tracer: "Tracer", name: str, args: Dict[str, Any]) -> None:
        self._tracer = tracer
        self.name = name
        self.args = args
        self.start = 0.0
        self.child_seconds = 0.0
        self.record: Optional[Dict[str, Any]] = None

    def __enter__(self) -> "_Span":
        self._tracer._push(self)
        self.start = self._tracer._clock()
        return self

    def __exit__(self, *exc_info) -> None:
        end = self._tracer._clock()
        self._tracer._pop(self, end)


class Tracer:
    """Collects host-side spans; exports Chrome trace JSON and summaries.

    :param enabled: ``False`` turns every :meth:`span` into a shared null
        context manager — the instrumentation stays in place at near-zero cost.
    """

    def __init__(self, enabled: bool = True) -> None:
        self.enabled = bool(enabled)
        self._clock = time.perf_counter
        self._t0 = self._clock()
        self._wall0 = time.time()
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._local = threading.local()

    # -- span recording ----------------------------------------------------- #
    def _stack(self) -> List[_Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = []
            self._local.stack = stack
        return stack

    def _push(self, span: _Span) -> None:
        self._stack().append(span)

    def _pop(self, span: _Span, end: float) -> None:
        stack = self._stack()
        # tolerate misnesting (a span closed out of order) instead of raising
        # from telemetry code: drop frames down to (and including) this span
        while stack:
            frame = stack.pop()
            if frame is span:
                break
        duration = max(end - span.start, 0.0)
        record = {
            "name": span.name,
            "tid": threading.get_ident(),
            "start": span.start - self._t0,
            "dur": duration,
            "self": max(duration - span.child_seconds, 0.0),
            "args": span.args,
        }
        span.record = record
        if stack:
            stack[-1].child_seconds += duration
        with self._lock:
            self._events.append(record)

    def span(self, name: str, **args: Any):
        """Context manager timing the enclosed block as span ``name``.

        Nested spans subtract from the parent's exclusive ("self") time, so
        summary totals over sibling categories never double-count.
        """
        if not self.enabled:
            return _NULL_CONTEXT
        return _Span(self, name, args)

    def add_span(
        self, name: str, start_seconds: float, duration_seconds: float, **args: Any
    ) -> None:
        """Record a synthetic span measured outside ``with`` blocks (``start``
        relative to the tracer's epoch, i.e. another span's ``record['start']``)."""
        if not self.enabled:
            return
        duration = max(float(duration_seconds), 0.0)
        with self._lock:
            self._events.append(
                {
                    "name": name,
                    "tid": threading.get_ident(),
                    "start": float(start_seconds),
                    "dur": duration,
                    "self": duration,
                    "args": args,
                }
            )

    def carve(self, span: _Span, name: str, seconds: float, **args: Any) -> None:
        """Re-attribute ``seconds`` of a finished span's self time to ``name``.

        The carved span is recorded nested at the parent's start (Chrome trace
        renders it inside), and the parent's exclusive time shrinks by the
        same amount — used to split compile wall-time out of the step that
        triggered the (re)trace.
        """
        if not self.enabled or span is None or span.record is None:
            return
        seconds = max(min(float(seconds), span.record["self"]), 0.0)
        if seconds <= 0.0:
            return
        with self._lock:
            span.record["self"] -= seconds
            self._events.append(
                {
                    "name": name,
                    "tid": span.record["tid"],
                    "start": span.record["start"],
                    "dur": seconds,
                    "self": seconds,
                    "args": args,
                }
            )

    # -- aggregation -------------------------------------------------------- #
    def now(self) -> float:
        """Epoch-relative timestamp (seconds since this tracer was created) —
        the time base of every recorded span's ``start``. Take one on the
        producing thread and hand it to :func:`lifecycle_span` on the consuming
        thread to time a phase whose begin/end straddle threads."""
        return self._clock() - self._t0

    def wall_seconds(self) -> float:
        """Seconds since this tracer was created."""
        return self._clock() - self._t0

    def summary(self, only_current_thread: bool = False) -> Dict[str, Dict[str, float]]:
        """``{name: {count, seconds, self_seconds}}`` over recorded spans
        (``seconds`` inclusive of children, ``self_seconds`` exclusive).

        ``only_current_thread`` restricts to spans recorded on the calling
        thread — what a wall-clock decomposition of THAT thread's time may
        count (work on other threads, e.g. a prefetch worker's
        ``batch_build``, overlaps it rather than consuming it).
        """
        tid = threading.get_ident() if only_current_thread else None
        with self._lock:
            events = list(self._events)
        out: Dict[str, Dict[str, float]] = {}
        for event in events:
            if tid is not None and event["tid"] != tid:
                continue
            entry = out.setdefault(
                event["name"], {"count": 0, "seconds": 0.0, "self_seconds": 0.0}
            )
            entry["count"] += 1
            entry["seconds"] += event["dur"]
            entry["self_seconds"] += event["self"]
        return out

    def snapshot(self, only_current_thread: bool = False) -> Dict[str, float]:
        """Per-name exclusive-seconds totals — diff two snapshots to window a
        breakdown over an epoch (see :func:`goodput_breakdown`)."""
        return {
            name: entry["self_seconds"]
            for name, entry in self.summary(only_current_thread).items()
        }

    # -- export ------------------------------------------------------------- #
    def to_chrome_trace(self) -> Dict[str, Any]:
        """Chrome trace-event JSON (the ``chrome://tracing`` / Perfetto format):
        complete events (``ph="X"``) with microsecond ``ts``/``dur``."""
        with self._lock:
            events = list(self._events)
        pid = os.getpid()
        trace_events = []
        for event in sorted(events, key=lambda e: e["start"]):
            record = {
                "name": event["name"],
                "cat": "host",
                "ph": "X",
                "ts": round(event["start"] * 1e6, 3),
                "dur": round(event["dur"] * 1e6, 3),
                "pid": pid,
                "tid": event["tid"],
            }
            if event["args"]:
                record["args"] = {str(k): v for k, v in event["args"].items()}
            trace_events.append(record)
        return {
            "traceEvents": trace_events,
            "displayTimeUnit": "ms",
            "otherData": {"trace_epoch_unix": self._wall0},
        }

    def save(self, path: str) -> str:
        """Write ``trace.json`` (Chrome trace-event JSON) to ``path``."""
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        with open(path, "w") as fh:
            json.dump(self.to_chrome_trace(), fh)
        return path


def traced_iterator(
    batches: Iterable[Any], tracer: Tracer, name: str = "data_wait"
) -> Iterator[Any]:
    """Yield from ``batches``, timing every ``next()`` as a ``name`` span.

    This is how the fit loop attributes host input time: the span covers
    exactly the wait for the batcher (prefetch queue pops included), not the
    consumer's work on the yielded batch.
    """
    iterator = iter(batches)
    while True:
        with tracer.span(name):
            try:
                batch = next(iterator)
            except StopIteration:
                return
        yield batch


def lifecycle_span(
    tracer: Tracer, name: str, started_at: float, **args: Any
) -> float:
    """Record a lifecycle phase that began on another thread; returns its
    duration in seconds.

    :func:`traced_iterator`'s cross-thread sibling: a request's ``queue_wait``
    starts when the client thread enqueues it (capture ``tracer.now()`` there)
    and ends when the serve worker dequeues it — no single ``with`` block can
    cover both, so the span is recorded synthetically on the consuming thread
    via :meth:`Tracer.add_span`.
    """
    duration = max(tracer.now() - float(started_at), 0.0)
    tracer.add_span(name, float(started_at), duration, **args)
    return duration


def merge_traces(
    shards: Mapping[str, Any], path: Optional[str] = None
) -> Dict[str, Any]:
    """Merge per-shard Chrome traces into ONE trace with labeled tracks.

    ``shards`` maps a track label ("router", "r0", ...) to a :class:`Tracer`
    or an already-exported Chrome trace dict. Each shard becomes its own
    process track: a distinct ``pid`` plus a ``process_name`` metadata event
    (``ph="M"``) carrying the label, which is how Perfetto titles the track.
    Shards run on independent ``perf_counter`` epochs; their timestamps are
    aligned onto the EARLIEST shard's epoch via each trace's
    ``otherData.trace_epoch_unix`` (the wall clock at tracer construction), so
    a request's router spans and its replica spans line up on one time axis.

    Returns the merged trace dict; when ``path`` is given also writes it
    there (the fleet's single ``trace.json``).
    """
    chrome: Dict[str, Dict[str, Any]] = {}
    for label, shard in shards.items():
        trace = shard.to_chrome_trace() if hasattr(shard, "to_chrome_trace") else shard
        chrome[str(label)] = trace
    epochs = {
        label: float((trace.get("otherData") or {}).get("trace_epoch_unix") or 0.0)
        for label, trace in chrome.items()
    }
    base_epoch = min(epochs.values()) if epochs else 0.0
    merged: List[Dict[str, Any]] = []
    tracks: Dict[str, int] = {}
    for index, (label, trace) in enumerate(chrome.items()):
        pid = index + 1
        tracks[label] = pid
        offset_us = (epochs[label] - base_epoch) * 1e6
        merged.append(
            {
                "name": "process_name",
                "ph": "M",
                "ts": 0,
                "pid": pid,
                "tid": 0,
                "args": {"name": label},
            }
        )
        for event in trace.get("traceEvents", ()):
            if event.get("ph") == "M":
                continue  # shard-local metadata is superseded by the track label
            record = dict(event)
            record["pid"] = pid
            record["ts"] = round(float(event.get("ts", 0.0)) + offset_us, 3)
            merged.append(record)
    merged.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0.0)))
    out = {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "otherData": {"trace_epoch_unix": base_epoch, "tracks": tracks},
    }
    if path is not None:
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        with open(path, "w") as fh:
            json.dump(out, fh)
    return out


def _event_trace_ids(event: Mapping[str, Any]) -> Tuple[str, ...]:
    """The request(s) a trace event belongs to: a scalar ``trace_id`` arg for
    per-request spans, a ``trace_ids`` list for batch-level spans shared by
    every co-riding request (each gets the full batch duration — request-
    centric attribution: "MY batch spent X ms scoring")."""
    args = event.get("args")
    if not args:
        return ()
    trace_id = args.get("trace_id")
    if trace_id:
        return (str(trace_id),)
    trace_ids = args.get("trace_ids")
    if trace_ids:
        return tuple(str(t) for t in trace_ids)
    return ()


def tail_attribution(
    trace_events: Iterable[Mapping[str, Any]],
    quantiles: Sequence[float] = (0.5, 0.99),
    root: str = "request",
    hops: Sequence[str] = REQUEST_HOP_SPANS,
) -> Optional[Dict[str, Any]]:
    """Decompose completed requests' latency into per-hop fractions.

    Groups Chrome trace events by ``trace_id``: each root span (``root``,
    recorded by the fleet router over a request's full submit→answer window)
    defines one completed request's latency; every hop span sharing its
    trace_id contributes its duration. Per request the hop fractions are
    clipped to the root window (concurrent hops — a hedge twin racing the
    primary — can overlap; renormalized like :func:`goodput_breakdown`) and
    the residual is ``other``, so each request's fractions sum to 1.0.

    For each quantile ``q`` the attribution is the MEAN hop mix over the
    slowest ``(1 - q)`` share of requests (nearest-rank tail subset): "what do
    the p99 requests spend their time on", not "what does the p99 request
    spend". Returns ``None`` when no root span carries a trace_id (tracing
    was off or nothing completed).
    """
    roots: Dict[str, float] = {}
    hop_seconds: Dict[str, Dict[str, float]] = {}
    hop_set = set(hops)
    for event in trace_events:
        if event.get("ph") == "M":
            continue
        name = event.get("name")
        ids = _event_trace_ids(event)
        if not ids:
            continue
        dur_s = max(float(event.get("dur") or 0.0), 0.0) / 1e6
        if name == root:
            roots[ids[0]] = max(roots.get(ids[0], 0.0), dur_s)
        elif name in hop_set:
            for tid in ids:
                per_hop = hop_seconds.setdefault(tid, {})
                per_hop[name] = per_hop.get(name, 0.0) + dur_s
    if not roots:
        return None
    per_request: List[Tuple[float, Dict[str, float]]] = []
    for trace_id, total in sorted(roots.items(), key=lambda kv: kv[1]):
        fractions: Dict[str, float] = {}
        tracked = 0.0
        per_hop = hop_seconds.get(trace_id, {})
        for name in hops:
            seconds = min(max(per_hop.get(name, 0.0), 0.0), total) if total > 0 else 0.0
            tracked += seconds
            fractions[name] = seconds / total if total > 0 else 0.0
        if total > 0 and tracked > total:
            for name in hops:
                fractions[name] *= total / tracked
            tracked = total
        fractions["other"] = (total - tracked) / total if total > 0 else 1.0
        per_request.append((total, fractions))
    n = len(per_request)
    out: Dict[str, Any] = {
        "requests": n,
        "hops": list(hops) + ["other"],
        "quantiles": {},
    }
    for q in quantiles:
        start = min(int(float(q) * n), n - 1)
        subset = per_request[start:]
        means: Dict[str, float] = {}
        for name in out["hops"]:
            means[name] = sum(f[name] for _, f in subset) / len(subset)
        # exact residual: the averaged mix must still sum to 1.0 bit-for-bit
        means["other"] = max(1.0 - sum(means[name] for name in hops), 0.0)
        key = f"p{int(round(float(q) * 100)):02d}"
        out["quantiles"][key] = {
            "latency_ms": subset[0][0] * 1e3,
            "n": len(subset),
            "fractions": means,
        }
    return out


def goodput_breakdown(
    span_self_seconds: Mapping[str, float],
    wall_seconds: float,
    spans: Iterable[str] = GOODPUT_SPANS,
) -> Dict[str, Any]:
    """Fold an exclusive-time snapshot (diff) into the goodput record.

    Returns ``{"wall_seconds", "fractions", "input_starvation"}`` where
    ``fractions`` maps every ``spans`` phase (default :data:`GOODPUT_SPANS`;
    pass :data:`SERVE_GOODPUT_SPANS` for a serving worker) plus the derived
    ``other`` to its share of ``wall_seconds`` — summing to 1.0 by
    construction — and ``input_starvation`` is the fraction of the stepping
    pipeline (data_wait + batch_build + h2d + compile + train_step) spent on
    the input side (waiting on the iterator + same-thread batch assembly).
    """
    spans = tuple(spans)
    wall = max(float(wall_seconds), 0.0)
    fractions: Dict[str, float] = {}
    tracked = 0.0
    for name in spans:
        seconds = max(float(span_self_seconds.get(name, 0.0)), 0.0)
        tracked += seconds
        fractions[name] = seconds / wall if wall > 0 else 0.0
    if wall > 0 and tracked > wall:
        # spans from concurrent threads can overlap the window; renormalize so
        # the contract (fractions sum to 1.0) survives
        for name in spans:
            fractions[name] *= wall / tracked
        tracked = wall
    fractions["other"] = (wall - tracked) / wall if wall > 0 else 1.0
    if "train_step" not in spans:
        # a non-training breakdown (e.g. SERVE_GOODPUT_SPANS) has no stepping
        # pipeline to starve — None keeps the metric honest and unrendered
        starvation = None
    else:
        pipeline = sum(
            max(float(span_self_seconds.get(name, 0.0)), 0.0) for name in _STEP_PIPELINE
        )
        input_side = sum(
            max(float(span_self_seconds.get(name, 0.0)), 0.0) for name in _INPUT_SPANS
        )
        starvation = input_side / pipeline if pipeline > 0 else 0.0
    return {
        "wall_seconds": wall,
        "fractions": fractions,
        "input_starvation": starvation,
    }
