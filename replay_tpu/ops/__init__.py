from .flash_attention import flash_attention, fused_attention_available

__all__ = ["flash_attention", "fused_attention_available"]
