from .flash_attention import flash_attention, fused_attention_available
from .fused_ce import fused_lse

__all__ = ["flash_attention", "fused_attention_available", "fused_lse"]
