"""Fused attention kernel (pallas, TPU). Beyond-parity: the reference has no
custom kernels (torch MultiheadAttention is its hot op, SURVEY.md §2.3); this
is the TPU-first replacement for that path.

The hot op of every sequential recommender here is the [B, H, L, L] attention.
XLA already fuses most of it; this kernel removes the HBM materialization of the
score matrix entirely on the FORWARD pass: each (batch, head) program computes
softmax(QKᵀ/√d + mask) · V inside VMEM with a numerically-stable single pass —
recsys sequence lengths (50-512) fit one VMEM block, so no KV loop is needed
(the ring-attention module handles the sharded long-context regime).

Training works through a ``jax.custom_vjp``: the backward pass recomputes the
attention weights in plain jnp (rematerialization — the standard flash-attention
trade: no stored score matrix on forward, one recompute on backward) and applies
the analytic softmax-attention gradients.

The additive mask stays [B, 1, L, L]; the grid reads the same mask block for
every head via its index map instead of broadcasting to [B, H, L, L] in HBM.

On non-TPU backends the kernel runs in interpreter mode (tests) — call sites
should prefer it only when ``jax.default_backend() == "tpu"``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _attention_kernel(q_ref, k_ref, v_ref, bias_ref, out_ref):
    """One (batch, head) program: fused masked softmax attention in VMEM."""
    q = q_ref[0, 0].astype(jnp.float32)  # [L, D]
    k = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0].astype(jnp.float32)
    bias = bias_ref[0, 0]  # [L, L] additive mask (causal+padding), float32
    scores = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32)) + bias
    row_max = jnp.max(scores, axis=-1, keepdims=True)
    probs = jnp.exp(scores - row_max)
    denom = jnp.maximum(jnp.sum(probs, axis=-1, keepdims=True), 1e-30)
    out = jnp.dot(probs / denom, v, preferred_element_type=jnp.float32)
    out_ref[0, 0] = out.astype(out_ref.dtype)


def _forward(q, k, v, bias, interpret):
    from jax.experimental import pallas as pl

    batch, heads, length, dim = q.shape
    bias = bias.astype(jnp.float32)
    bias_heads = bias.shape[1]

    block = lambda: pl.BlockSpec((1, 1, length, dim), lambda b, h: (b, h, 0, 0))
    # head-invariant masks ([B, 1, L, L]) are re-read per head, never broadcast
    bias_block = pl.BlockSpec(
        (1, 1, length, length),
        (lambda b, h: (b, h, 0, 0)) if bias_heads > 1 else (lambda b, h: (b, 0, 0, 0)),
    )
    return pl.pallas_call(
        _attention_kernel,
        grid=(batch, heads),
        in_specs=[block(), block(), block(), bias_block],
        out_specs=block(),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v, bias)


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def flash_attention(
    q: jnp.ndarray,  # [B, H, L, D]
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray,  # [B, 1 or H, L, L] additive mask
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused softmax attention; drop-in for the unfused jnp path, trainable."""
    return _forward(q, k, v, bias, interpret)


def _flash_fwd(q, k, v, bias, interpret):
    return _forward(q, k, v, bias, interpret), (q, k, v, bias)


def _flash_bwd(interpret, residuals, grad_out):
    q, k, v, bias = residuals
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    qf, kf, vf, g = (t.astype(jnp.float32) for t in (q, k, v, grad_out))
    # rematerialize the attention weights (XLA fuses this backward chain)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale + bias.astype(jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1)
    grad_v = jnp.einsum("bhqk,bhqd->bhkd", probs, g)
    grad_probs = jnp.einsum("bhqd,bhkd->bhqk", g, vf)
    # softmax backward: dS = P * (dP - sum_k dP * P)
    grad_scores = probs * (grad_probs - jnp.sum(grad_probs * probs, axis=-1, keepdims=True))
    grad_q = jnp.einsum("bhqk,bhkd->bhqd", grad_scores, kf) * scale
    grad_k = jnp.einsum("bhqk,bhqd->bhkd", grad_scores, qf) * scale
    grad_bias = grad_scores
    if bias.shape[1] == 1:  # head-invariant mask: sum the broadcast axis
        grad_bias = jnp.sum(grad_bias, axis=1, keepdims=True)
    return (
        grad_q.astype(q.dtype),
        grad_k.astype(k.dtype),
        grad_v.astype(v.dtype),
        grad_bias.astype(bias.dtype),
    )


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def fused_attention_available() -> bool:
    """True when the real (compiled) kernel can run on the current backend."""
    return jax.default_backend() == "tpu"
