"""Tiled flash attention (pallas, TPU): the LONG-sequence single-chip kernel.

The single-block kernel (ops/flash_attention.py) holds the whole [L, L] score
matrix of one (batch, head) in VMEM — past L≈1024 that exceeds the ~16 MB VMEM
budget (BENCH_NOTES round-3 A/B). This kernel implements the standard flash
recipe instead: grid ``(B, H, q_blocks, kv_blocks)`` with the kv axis innermost
(sequential on TPU), carrying the online-softmax state (running max, running
sum, output accumulator) in VMEM scratch across kv steps. VMEM peak is
O(block_q · block_k + block·D), independent of L, and nothing O(L²) ever
exists — not even the mask, which is computed in-kernel from block indices
(causal) plus a per-KEY additive bias row ([B, L], typically 0 / -1e30 from a
padding mask) instead of the [B, 1, L, L] bias tensor of the short-L kernel.

Training: ``jax.custom_vjp`` with the memory-efficient blockwise backward —
a ``lax.scan`` over kv blocks recomputing each block's probabilities from the
saved logsumexp (O(B·H·L·block_k) peak, never O(L²)).

Beyond-parity: the reference has no custom kernels; its torch path
materializes [B, H, L, L] (SURVEY.md §2.3). The mesh-sharded regime is ring
attention (replay_tpu/parallel/ring.py); this kernel is the within-chip story.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _kernel(q_ref, k_ref, v_ref, bias_ref, out_ref, lse_ref, m_ref, l_ref, acc_ref,
            *, block_q, block_k, num_k, causal):
    ki = pl.program_id(3)
    qi = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _accumulate():
        q = q_ref[0, 0].astype(jnp.float32)  # [bq, D]
        k = k_ref[0, 0].astype(jnp.float32)  # [bk, D]
        v = v_ref[0, 0].astype(jnp.float32)  # [bk, D]
        scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
        scores = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale  # [bq, bk]
        scores = scores + bias_ref[0][None, :]  # per-key bias (padding)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
            scores = jnp.where(cols <= rows, scores, NEG_INF)

        m_prev = m_ref[:, 0][:, None]  # [bq, 1]
        l_prev = l_ref[:, 0][:, None]
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1, keepdims=True))
        # fully-masked rows keep m == NEG_INF; exp(NEG_INF - NEG_INF) would be
        # 1, so mask the probabilities explicitly
        probs = jnp.exp(scores - m_new)
        probs = jnp.where(scores <= NEG_INF / 2, 0.0, probs)
        correction = jnp.where(m_prev <= NEG_INF / 2, 0.0, jnp.exp(m_prev - m_new))
        l_new = l_prev * correction + jnp.sum(probs, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * correction + jnp.dot(
            probs, v, preferred_element_type=jnp.float32
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # kv blocks entirely ABOVE the diagonal contribute nothing: skip both
        # matmuls (≈2× less causal work); init/finalize still run every step
        pl.when(ki * block_k <= qi * block_q + block_q - 1)(_accumulate)
    else:
        _accumulate()

    @pl.when(ki == num_k - 1)
    def _finalize():
        l_final = l_ref[:, 0][:, None]
        m_final = m_ref[:, 0][:, None]
        denom = jnp.maximum(l_final, 1e-30)
        out_ref[0, 0] = (acc_ref[...] / denom).astype(out_ref.dtype)
        # logsumexp residual for the blockwise backward; NEG_INF on dead rows
        lse = jnp.where(m_final <= NEG_INF / 2, NEG_INF, m_final + jnp.log(denom))
        lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:])


def _pad_to(x, axis, multiple, value=0.0):
    length = x.shape[axis]
    pad = (-length) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _forward(q, k, v, kv_bias, causal, block_q, block_k, interpret):
    batch, heads, length, dim = q.shape
    block_q = min(block_q, max(length, 1))
    block_k = min(block_k, max(length, 1))
    qp = _pad_to(q, 2, block_q)
    kp = _pad_to(k, 2, block_k)
    vp = _pad_to(v, 2, block_k)
    bias = _pad_to(kv_bias.astype(jnp.float32), 1, block_k, value=NEG_INF)
    lq, lk = qp.shape[2], kp.shape[2]
    num_q, num_k = lq // block_q, lk // block_k

    grid = (batch, heads, num_q, num_k)
    qspec = pl.BlockSpec((1, 1, block_q, dim), lambda b, h, i, j: (b, h, i, 0))
    kspec = pl.BlockSpec((1, 1, block_k, dim), lambda b, h, i, j: (b, h, j, 0))
    bspec = pl.BlockSpec((1, block_k), lambda b, h, i, j: (b, j))
    out_spec = pl.BlockSpec((1, 1, block_q, dim), lambda b, h, i, j: (b, h, i, 0))
    lse_spec = pl.BlockSpec((1, 1, block_q, 128), lambda b, h, i, j: (b, h, i, 0))

    from jax.experimental.pallas import tpu as pltpu

    scratch = [
        pltpu.VMEM((block_q, 128), jnp.float32),  # running max
        pltpu.VMEM((block_q, 128), jnp.float32),  # running sum
        pltpu.VMEM((block_q, dim), jnp.float32),  # output accumulator
    ]
    out, lse = pl.pallas_call(
        partial(_kernel, block_q=block_q, block_k=block_k, num_k=num_k, causal=causal),
        grid=grid,
        in_specs=[qspec, kspec, kspec, bspec],
        out_specs=[out_spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct(qp.shape, q.dtype),
            jax.ShapeDtypeStruct((batch, heads, lq, 128), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(qp, kp, vp, bias)
    return out[:, :, :length], lse[:, :, :length, 0]


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def flash_attention_tiled(
    q: jnp.ndarray,  # [B, H, L, D]
    k: jnp.ndarray,
    v: jnp.ndarray,
    kv_bias: jnp.ndarray,  # [B, L] additive per-key bias (0 valid / -1e30 pad)
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
) -> jnp.ndarray:
    """Length-tiled fused attention; VMEM and HBM stay O(L·block), not O(L²)."""
    out, _ = _forward(q, k, v, kv_bias, causal, block_q, block_k, interpret)
    return out


def padding_mask_bias(padding_mask: jnp.ndarray) -> jnp.ndarray:
    """[B, L] bool (True = real token) → the additive per-key bias row."""
    return jnp.where(padding_mask, 0.0, NEG_INF).astype(jnp.float32)


def _fwd(q, k, v, kv_bias, causal, block_q, block_k, interpret):
    out, lse = _forward(q, k, v, kv_bias, causal, block_q, block_k, interpret)
    return out, (q, k, v, kv_bias, out, lse)


def _bwd(causal, block_q, block_k, interpret, residuals, g):
    q, k, v, kv_bias, out, lse = residuals
    del block_q, interpret
    batch, heads, length, dim = q.shape
    qf, kf, vf, gf = (t.astype(jnp.float32) for t in (q, k, v, g))
    scale = 1.0 / jnp.sqrt(jnp.asarray(dim, jnp.float32))
    delta = jnp.sum(gf * out.astype(jnp.float32), axis=-1)  # [B, H, L]
    rows = jnp.arange(length)

    block = min(block_k, max(length, 1))
    pad = (-length) % block
    kp = _pad_to(kf, 2, block)
    vp = _pad_to(vf, 2, block)
    bias_p = _pad_to(kv_bias.astype(jnp.float32), 1, block, value=NEG_INF)
    num_k = kp.shape[2] // block
    # scan axis (kv block) must LEAD; keep [B, H, bk, D] intact behind it
    k_blocks = jnp.moveaxis(kp.reshape(batch, heads, num_k, block, dim), 2, 0)
    v_blocks = jnp.moveaxis(vp.reshape(batch, heads, num_k, block, dim), 2, 0)
    bias_blocks = bias_p.reshape(batch, num_k, block).swapaxes(0, 1)

    def step(dq_acc, inputs):
        j, kj, vj, bj = inputs  # kj/vj [B, H, bk, D], bj [B, bk]
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kj) * scale + bj[:, None, None, :]
        if causal:
            cols = j * block + jnp.arange(block)
            s = jnp.where(cols[None, None, None, :] <= rows[None, None, :, None], s, NEG_INF)
        p = jnp.exp(s - lse[..., None])
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        dv_j = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf, vj)
        ds = p * (dp - delta[..., None])
        dk_j = jnp.einsum("bhqk,bhqd->bhkd", ds, qf) * scale
        dbias_j = jnp.sum(ds, axis=(1, 2))  # [B, bk]
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds, kj) * scale
        return dq_acc, (dk_j, dv_j, dbias_j)

    dq, (dk_b, dv_b, dbias_b) = jax.lax.scan(
        step,
        jnp.zeros_like(qf),
        (jnp.arange(num_k), k_blocks, v_blocks, bias_blocks),
    )
    dk = jnp.moveaxis(dk_b, 0, 2).reshape(batch, heads, num_k * block, dim)[:, :, :length]
    dv = jnp.moveaxis(dv_b, 0, 2).reshape(batch, heads, num_k * block, dim)[:, :, :length]
    dbias = dbias_b.swapaxes(0, 1).reshape(batch, num_k * block)[:, :length]
    return (
        dq.astype(q.dtype),
        dk.astype(k.dtype),
        dv.astype(v.dtype),
        dbias.astype(kv_bias.dtype),
    )


flash_attention_tiled.defvjp(_fwd, _bwd)
