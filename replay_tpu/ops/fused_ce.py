"""Fused full-softmax log-sum-exp over the item catalog (pallas, TPU).

Beyond-parity: the reference computes full-catalog CE by materializing
``[B, L, num_items]`` logits (replay/nn/loss/ce.py:10 via a torch linear head).
At recsys scales that tensor dominates the train step's HBM traffic — for the
notebook-09 config it is ~190 MB per step against a 474 KB item table; at
ML-20M scale it is gigabytes. This kernel computes
``lse_n = logsumexp_i(h_n · w_i)`` tile-by-tile in VMEM with a flash-style
online max/sum over catalog tiles, so neither axis is ever resident in full:
HBM sees only the hidden states, the table, and one scalar per row.

Training works through ``jax.custom_vjp`` with rematerialization: the forward
saves only ``lse`` alongside the inputs, and two backward kernels recompute
each ``[row_tile, item_tile]`` logits block on the fly —

- ``dh = (g · softmax) @ W`` gridded (rows, items) so the dh block accumulates
  over the consecutive inner item axis;
- ``dW = (g · softmax)ᵀ @ h`` gridded (items, rows) so the dW block accumulates
  over the consecutive inner row axis.

(TPU pallas grids execute sequentially, which is what makes same-block
accumulation across the inner axis well-defined.)

On non-TPU backends the kernels run in interpreter mode (tests); call sites
should prefer them only when ``jax.default_backend() == "tpu"``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

_LANE = 128  # TPU lane width: catalog axis is padded to a multiple of this
_DEFAULT_ITEM_TILE = 4096  # catalog tiles: [row_tile, item_tile] logits blocks


def _pad_to(value: int, multiple: int) -> int:
    return ((value + multiple - 1) // multiple) * multiple


def _masked_logits(num_items_ref, h_ref, w_ref, item_tile: int):
    """One [T, item_tile] logits block with catalog padding masked to -inf.

    The mask is a [1, item_tile] row vector (a few KB) rather than a full-size
    iota compare, which would cost as much VMEM as the logits block itself.
    """
    from jax.experimental import pallas as pl

    h = h_ref[...].astype(jnp.float32)  # [T, E]
    w = w_ref[...].astype(jnp.float32)  # [item_tile, E]
    logits = jnp.dot(h, w.T, preferred_element_type=jnp.float32)
    col = pl.program_id(1) * item_tile + jax.lax.broadcasted_iota(
        jnp.int32, (1, item_tile), 1
    )
    return logits + jnp.where(col < num_items_ref[0], 0.0, -jnp.inf).astype(jnp.float32)


def _lse_kernel(num_items_ref, h_ref, w_ref, lse_ref, m_ref, s_ref):
    """Online logsumexp: running max/sum scratch across the inner item grid."""
    from jax.experimental import pallas as pl

    j, num_j = pl.program_id(1), pl.num_programs(1)

    @pl.when(j == 0)
    def _reset():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        s_ref[...] = jnp.zeros_like(s_ref)

    logits = _masked_logits(num_items_ref, h_ref, w_ref, w_ref.shape[0])
    tile_max = jnp.max(logits, axis=-1, keepdims=True)  # finite: every tile
    new_max = jnp.maximum(m_ref[...], tile_max)  # has >=1 real column
    s_ref[...] = s_ref[...] * jnp.exp(m_ref[...] - new_max) + jnp.sum(
        jnp.exp(logits - new_max), axis=-1, keepdims=True
    )
    m_ref[...] = new_max

    @pl.when(j == num_j - 1)
    def _finalize():
        lse_ref[...] = m_ref[...] + jnp.log(s_ref[...])


def _dh_kernel(num_items_ref, h_ref, w_ref, g_ref, lse_ref, dh_ref):
    """dh[i] = sum_j (g * softmax_block_j) @ W_j — inner item axis accumulates."""
    from jax.experimental import pallas as pl

    logits = _masked_logits(num_items_ref, h_ref, w_ref, w_ref.shape[0])
    weighted = jnp.exp(logits - lse_ref[...]) * g_ref[...].astype(jnp.float32)
    # f32 accumulation across catalog tiles (dh_ref is f32; the caller casts to
    # hidden.dtype once after the kernel, mirroring the dW path)
    contrib = jnp.dot(
        weighted, w_ref[...].astype(jnp.float32), preferred_element_type=jnp.float32
    )

    @pl.when(pl.program_id(1) == 0)
    def _init():
        dh_ref[...] = contrib

    @pl.when(pl.program_id(1) != 0)
    def _accumulate():
        dh_ref[...] += contrib


def _dw_kernel(num_items_ref, h_ref, w_ref, g_ref, lse_ref, dw_ref):
    """dW[j] = sum_i (g * softmax_block)ᵀ @ h_i — inner row axis accumulates.

    Grid is (items, rows): program_id(0) is the item tile, program_id(1) the
    row tile, so ``_masked_logits``'s column offset uses program_id(0) here —
    handled by swapping the id axes via the transposed wrapper below.
    """
    from jax.experimental import pallas as pl

    h = h_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    logits = jnp.dot(h, w.T, preferred_element_type=jnp.float32)
    item_tile = w.shape[0]
    col = pl.program_id(0) * item_tile + jax.lax.broadcasted_iota(
        jnp.int32, (1, item_tile), 1
    )
    logits = logits + jnp.where(col < num_items_ref[0], 0.0, -jnp.inf).astype(jnp.float32)
    weighted = jnp.exp(logits - lse_ref[...]) * g_ref[...].astype(jnp.float32)
    contrib = jnp.dot(weighted.T, h, preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(1) == 0)
    def _init():
        dw_ref[...] = contrib

    @pl.when(pl.program_id(1) != 0)
    def _accumulate():
        dw_ref[...] += contrib


def _prepare(hidden: jnp.ndarray, table: jnp.ndarray, tile: int, item_tile: int):
    n, embed = hidden.shape
    num_items = table.shape[0]
    n_pad = _pad_to(max(n, 1), tile)
    items_pad = _pad_to(max(num_items, 1), item_tile)
    hidden = jnp.pad(hidden, ((0, n_pad - n), (0, 0)))
    table = jnp.pad(table, ((0, items_pad - num_items), (0, 0)))
    return hidden, table, n, n_pad, items_pad, embed, num_items


def _resolve_item_tile(num_items: int, item_tile) -> int:
    if item_tile is None:
        item_tile = _DEFAULT_ITEM_TILE
    return min(_pad_to(item_tile, _LANE), _pad_to(max(num_items, 1), _LANE))


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def fused_lse(
    hidden: jnp.ndarray,
    table: jnp.ndarray,
    tile: int = 256,
    item_tile: int = None,
    interpret: bool = False,
):
    """``logsumexp(hidden @ table.T, axis=-1)`` without materializing the logits.

    :param hidden: ``[N, E]`` row vectors (any float dtype; f32 accumulation).
    :param table: ``[num_items, E]`` item embeddings.
    :param tile: rows per program.
    :param item_tile: catalog columns per program (defaults to 4096; the
        catalog is swept with an online max/sum so any size compiles).
    :return: ``[N]`` float32 log-sum-exp values.
    """
    return _run_forward(hidden, table, tile, item_tile, interpret)


def _run_forward(hidden, table, tile, item_tile, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    item_tile = _resolve_item_tile(table.shape[0], item_tile)
    hidden_p, table_p, n, n_pad, items_pad, embed, num_items = _prepare(
        hidden, table, tile, item_tile
    )
    grid = (n_pad // tile, items_pad // item_tile)
    lse = pl.pallas_call(
        _lse_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((tile, embed), lambda i, j, *_: (i, 0)),
                pl.BlockSpec((item_tile, embed), lambda i, j, *_: (j, 0)),
            ],
            out_specs=pl.BlockSpec((tile, 1), lambda i, j, *_: (i, 0)),
            scratch_shapes=[
                pltpu.VMEM((tile, 1), jnp.float32),
                pltpu.VMEM((tile, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        interpret=interpret,
    )(jnp.asarray([num_items], jnp.int32), hidden_p, table_p)
    return lse[:n, 0]


def _fused_lse_fwd(hidden, table, tile, item_tile, interpret):
    lse = _run_forward(hidden, table, tile, item_tile, interpret)
    return lse, (hidden, table, lse)


def _fused_lse_bwd(tile, item_tile, interpret, residuals, grad_lse):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    hidden, table, lse = residuals
    item_tile = _resolve_item_tile(table.shape[0], item_tile)
    hidden_p, table_p, n, n_pad, items_pad, embed, num_items = _prepare(
        hidden, table, tile, item_tile
    )
    rows, items = n_pad // tile, items_pad // item_tile
    g = jnp.pad(grad_lse.astype(jnp.float32), (0, n_pad - n)).reshape(n_pad, 1)
    lse_p = jnp.pad(lse, (0, n_pad - n)).reshape(n_pad, 1)
    scalar = jnp.asarray([num_items], jnp.int32)

    dh = pl.pallas_call(
        _dh_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(rows, items),
            in_specs=[
                pl.BlockSpec((tile, embed), lambda i, j, *_: (i, 0)),
                pl.BlockSpec((item_tile, embed), lambda i, j, *_: (j, 0)),
                pl.BlockSpec((tile, 1), lambda i, j, *_: (i, 0)),
                pl.BlockSpec((tile, 1), lambda i, j, *_: (i, 0)),
            ],
            out_specs=pl.BlockSpec((tile, embed), lambda i, j, *_: (i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, embed), jnp.float32),
        interpret=interpret,
    )(scalar, hidden_p, table_p, g, lse_p)

    dw = pl.pallas_call(
        _dw_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(items, rows),
            in_specs=[
                pl.BlockSpec((tile, embed), lambda j, i, *_: (i, 0)),
                pl.BlockSpec((item_tile, embed), lambda j, i, *_: (j, 0)),
                pl.BlockSpec((tile, 1), lambda j, i, *_: (i, 0)),
                pl.BlockSpec((tile, 1), lambda j, i, *_: (i, 0)),
            ],
            out_specs=pl.BlockSpec((item_tile, embed), lambda j, i, *_: (j, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((items_pad, embed), jnp.float32),
        interpret=interpret,
    )(scalar, hidden_p, table_p, g, lse_p)

    return dh[:n].astype(hidden.dtype), dw[:num_items].astype(table.dtype)


fused_lse.defvjp(_fused_lse_fwd, _fused_lse_bwd)
