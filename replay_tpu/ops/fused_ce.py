"""Fused full-softmax log-sum-exp over the item catalog (pallas, TPU).

Beyond-parity: the reference computes full-catalog CE by materializing
``[B, L, num_items]`` logits (replay/nn/loss/ce.py:10 via a torch linear head).
At recsys scales that tensor dominates the train step's HBM traffic — for the
notebook-09 config it is ~190 MB per step against a 474 KB item table; at
ML-20M scale it is gigabytes. This kernel computes
``lse_n = logsumexp_i(h_n · w_i)`` tile-by-tile in VMEM with a flash-style
online max/sum over catalog tiles, so neither axis is ever resident in full:
HBM sees only the hidden states, the table, and one scalar per row.

Training works through ``jax.custom_vjp`` with rematerialization: the forward
saves only ``lse`` alongside the inputs, and two backward kernels recompute
each ``[row_tile, item_tile]`` logits block on the fly —

- ``dh = (g · softmax) @ W`` gridded (rows, items) so the dh block accumulates
  over the consecutive inner item axis;
- ``dW = (g · softmax)ᵀ @ h`` gridded (items, rows) so the dW block accumulates
  over the consecutive inner row axis.

(TPU pallas grids execute sequentially, which is what makes same-block
accumulation across the inner axis well-defined.)

Two provisions for callers beyond the single-device case:

- ``num_valid`` may be a TRACED int32 scalar smaller than ``table.shape[0]``:
  the vocab-sharded wrapper (replay_tpu.parallel.sharded_ce) gives each shard
  a fixed-shape ``[I/n_tp, E]`` slice but a per-shard valid count derived from
  ``lax.axis_index`` at run time. Padding columns are masked with a large
  FINITE negative (``_MASK``) rather than −inf, so a shard whose slice is
  entirely padding still produces a well-defined (≈ −1e30) lse instead of
  NaN-ing the online max/sum; ``exp(_MASK − lse)`` underflows to exactly 0.0
  for any realistic lse, so results are bit-identical to the −inf mask.
- a VMEM-budget guard: the ``[row_tile, item_tile]`` working set is estimated
  up front and ``item_tile`` auto-shrinks (lane-aligned halving) instead of
  failing at Mosaic compile time (the round-3 16 MB bwd-kernel incident); one
  warning is logged per shrunk configuration.

On non-TPU backends the kernels run in interpreter mode (tests); call sites
should prefer them only when ``jax.default_backend() == "tpu"``.
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger("replay_tpu")

_LANE = 128  # TPU lane width: catalog axis is padded to a multiple of this
_DEFAULT_ITEM_TILE = 4096  # catalog tiles: [row_tile, item_tile] logits blocks
# finite catalog-padding mask: exp(_MASK - lse) == 0.0 exactly for any
# realistic lse (f32 exp underflows below ~-104), so real rows are
# bit-identical to a -inf mask, while a FULLY-masked shard (the TP wrapper's
# empty tail shard) still yields a finite ~-1e30 lse instead of NaN
_MASK = -1e30
# per-core VMEM budget for one kernel invocation: 16 MiB of VMEM minus
# headroom for Mosaic's own buffers — exceeding it fails at compile time.
# Calibrated against the round-3 evidence: [256, 4096] at E=64 compiled and
# ran (≈8 MB by the model below), the E=300 bwd kernel at the same tile
# (≈24 MB) died at the 16 MB limit.
_VMEM_BUDGET_BYTES = 14 * 1024 * 1024
_shrink_warned: Set[Tuple[int, int, int, int]] = set()


def _pad_to(value: int, multiple: int) -> int:
    return ((value + multiple - 1) // multiple) * multiple


def _working_set_bytes(tile: int, item_tile: int, embed: int) -> int:
    """Estimated peak VMEM of one grid step of the WORST kernel (the
    backwards): pipeline blocks (h/w/g/lse in, dh-or-dw out — double-buffered
    by Mosaic) plus the f32 [tile, item_tile] logits intermediate (its
    softmax-weighted successor reuses the buffer), all f32."""
    blocks = 2 * (tile * embed + item_tile * embed) + 2 * tile
    return 4 * (2 * blocks + tile * item_tile)


def _resolve_item_tile(num_items: int, item_tile, tile: int, embed: int) -> int:
    """Lane-align the catalog tile and shrink it to the VMEM budget.

    The guard runs BEFORE the kernel is built: the round-3 incident was a
    [256, 4096] bwd block at d=300 blowing the 16 MB Mosaic limit at compile
    time — opaque to the caller. Halving keeps lane alignment; one warning per
    shrunk configuration records the decision in the run log.
    """
    requested = _DEFAULT_ITEM_TILE if item_tile is None else item_tile
    resolved = min(_pad_to(requested, _LANE), _pad_to(max(num_items, 1), _LANE))
    shrunk = resolved
    while shrunk > _LANE and _working_set_bytes(tile, shrunk, embed) > _VMEM_BUDGET_BYTES:
        shrunk = _pad_to(shrunk // 2, _LANE)
    if shrunk != resolved:
        key = (tile, resolved, shrunk, embed)
        if key not in _shrink_warned:
            _shrink_warned.add(key)
            logger.warning(
                "fused_ce: item_tile %d would need ~%.1f MB of VMEM at "
                "row_tile=%d, embed=%d (budget %.0f MB): shrunk to %d. Pass "
                "item_tile= explicitly to silence.",
                resolved,
                _working_set_bytes(tile, resolved, embed) / 2**20,
                tile,
                embed,
                _VMEM_BUDGET_BYTES / 2**20,
                shrunk,
            )
    return shrunk


def _masked_logits(num_valid_ref, h_ref, w_ref, item_tile: int):
    """One [T, item_tile] logits block with catalog padding masked to _MASK.

    The mask is a [1, item_tile] row vector (a few KB) rather than a full-size
    iota compare, which would cost as much VMEM as the logits block itself.
    """
    from jax.experimental import pallas as pl

    h = h_ref[...].astype(jnp.float32)  # [T, E]
    w = w_ref[...].astype(jnp.float32)  # [item_tile, E]
    logits = jnp.dot(h, w.T, preferred_element_type=jnp.float32)
    col = pl.program_id(1) * item_tile + jax.lax.broadcasted_iota(
        jnp.int32, (1, item_tile), 1
    )
    return logits + jnp.where(col < num_valid_ref[0], 0.0, _MASK).astype(jnp.float32)


def _lse_kernel(num_valid_ref, h_ref, w_ref, lse_ref, m_ref, s_ref):
    """Online logsumexp: running max/sum scratch across the inner item grid."""
    from jax.experimental import pallas as pl

    j, num_j = pl.program_id(1), pl.num_programs(1)

    @pl.when(j == 0)
    def _reset():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        s_ref[...] = jnp.zeros_like(s_ref)

    logits = _masked_logits(num_valid_ref, h_ref, w_ref, w_ref.shape[0])
    tile_max = jnp.max(logits, axis=-1, keepdims=True)  # finite even for a
    new_max = jnp.maximum(m_ref[...], tile_max)  # fully-masked tile (_MASK)
    s_ref[...] = s_ref[...] * jnp.exp(m_ref[...] - new_max) + jnp.sum(
        jnp.exp(logits - new_max), axis=-1, keepdims=True
    )
    m_ref[...] = new_max

    @pl.when(j == num_j - 1)
    def _finalize():
        lse_ref[...] = m_ref[...] + jnp.log(s_ref[...])


def _dh_kernel(num_valid_ref, h_ref, w_ref, g_ref, lse_ref, dh_ref):
    """dh[i] = sum_j (g * softmax_block_j) @ W_j — inner item axis accumulates."""
    from jax.experimental import pallas as pl

    logits = _masked_logits(num_valid_ref, h_ref, w_ref, w_ref.shape[0])
    weighted = jnp.exp(logits - lse_ref[...]) * g_ref[...].astype(jnp.float32)
    # f32 accumulation across catalog tiles (dh_ref is f32; the caller casts to
    # hidden.dtype once after the kernel, mirroring the dW path)
    contrib = jnp.dot(
        weighted, w_ref[...].astype(jnp.float32), preferred_element_type=jnp.float32
    )

    @pl.when(pl.program_id(1) == 0)
    def _init():
        dh_ref[...] = contrib

    @pl.when(pl.program_id(1) != 0)
    def _accumulate():
        dh_ref[...] += contrib


def _dw_kernel(num_valid_ref, h_ref, w_ref, g_ref, lse_ref, dw_ref):
    """dW[j] = sum_i (g * softmax_block)ᵀ @ h_i — inner row axis accumulates.

    Grid is (items, rows): program_id(0) is the item tile, program_id(1) the
    row tile, so the column offset uses program_id(0) here.
    """
    from jax.experimental import pallas as pl

    h = h_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    logits = jnp.dot(h, w.T, preferred_element_type=jnp.float32)
    item_tile = w.shape[0]
    col = pl.program_id(0) * item_tile + jax.lax.broadcasted_iota(
        jnp.int32, (1, item_tile), 1
    )
    logits = logits + jnp.where(col < num_valid_ref[0], 0.0, _MASK).astype(jnp.float32)
    weighted = jnp.exp(logits - lse_ref[...]) * g_ref[...].astype(jnp.float32)
    contrib = jnp.dot(weighted.T, h, preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(1) == 0)
    def _init():
        dw_ref[...] = contrib

    @pl.when(pl.program_id(1) != 0)
    def _accumulate():
        dw_ref[...] += contrib


def _prepare(hidden: jnp.ndarray, table: jnp.ndarray, tile: int, item_tile: int):
    n, embed = hidden.shape
    num_rows = table.shape[0]
    n_pad = _pad_to(max(n, 1), tile)
    items_pad = _pad_to(max(num_rows, 1), item_tile)
    hidden = jnp.pad(hidden, ((0, n_pad - n), (0, 0)))
    table = jnp.pad(table, ((0, items_pad - num_rows), (0, 0)))
    return hidden, table, n, n_pad, items_pad, embed, num_rows


def fused_lse(
    hidden: jnp.ndarray,
    table: jnp.ndarray,
    tile: int = 256,
    item_tile: Optional[int] = None,
    interpret: bool = False,
    num_valid=None,
):
    """``logsumexp(hidden @ table.T, axis=-1)`` without materializing the logits.

    :param hidden: ``[N, E]`` row vectors (any float dtype; f32 accumulation).
    :param table: ``[num_items, E]`` item embeddings.
    :param tile: rows per program.
    :param item_tile: catalog columns per program (defaults to 4096, shrunk
        lane-aligned to the VMEM budget; the catalog is swept with an online
        max/sum so any size compiles).
    :param num_valid: valid leading rows of ``table`` — everything past it is
        masked out of the softmax. May be a TRACED int32 scalar (the
        vocab-sharded wrapper's per-shard count); default: all rows.
    :return: ``[N]`` float32 log-sum-exp values.
    """
    if num_valid is None:
        num_valid = table.shape[0]
    return _fused_lse(
        hidden, table, jnp.asarray(num_valid, jnp.int32), tile, item_tile, interpret
    )


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_lse(hidden, table, num_valid, tile, item_tile, interpret):
    return _run_forward(hidden, table, num_valid, tile, item_tile, interpret)


def _run_forward(hidden, table, num_valid, tile, item_tile, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    item_tile = _resolve_item_tile(table.shape[0], item_tile, tile, hidden.shape[1])
    hidden_p, table_p, n, n_pad, items_pad, embed, _ = _prepare(
        hidden, table, tile, item_tile
    )
    grid = (n_pad // tile, items_pad // item_tile)
    lse = pl.pallas_call(
        _lse_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((tile, embed), lambda i, j, *_: (i, 0)),
                pl.BlockSpec((item_tile, embed), lambda i, j, *_: (j, 0)),
            ],
            out_specs=pl.BlockSpec((tile, 1), lambda i, j, *_: (i, 0)),
            scratch_shapes=[
                pltpu.VMEM((tile, 1), jnp.float32),
                pltpu.VMEM((tile, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        interpret=interpret,
    )(jnp.reshape(num_valid, (1,)), hidden_p, table_p)
    return lse[:n, 0]


def _fused_lse_fwd(hidden, table, num_valid, tile, item_tile, interpret):
    lse = _run_forward(hidden, table, num_valid, tile, item_tile, interpret)
    return lse, (hidden, table, num_valid, lse)


def _fused_lse_bwd(tile, item_tile, interpret, residuals, grad_lse):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    hidden, table, num_valid, lse = residuals
    item_tile = _resolve_item_tile(table.shape[0], item_tile, tile, hidden.shape[1])
    hidden_p, table_p, n, n_pad, items_pad, embed, num_rows = _prepare(
        hidden, table, tile, item_tile
    )
    rows, items = n_pad // tile, items_pad // item_tile
    g = jnp.pad(grad_lse.astype(jnp.float32), (0, n_pad - n)).reshape(n_pad, 1)
    lse_p = jnp.pad(lse, (0, n_pad - n)).reshape(n_pad, 1)
    scalar = jnp.reshape(num_valid, (1,))

    dh = pl.pallas_call(
        _dh_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(rows, items),
            in_specs=[
                pl.BlockSpec((tile, embed), lambda i, j, *_: (i, 0)),
                pl.BlockSpec((item_tile, embed), lambda i, j, *_: (j, 0)),
                pl.BlockSpec((tile, 1), lambda i, j, *_: (i, 0)),
                pl.BlockSpec((tile, 1), lambda i, j, *_: (i, 0)),
            ],
            out_specs=pl.BlockSpec((tile, embed), lambda i, j, *_: (i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, embed), jnp.float32),
        interpret=interpret,
    )(scalar, hidden_p, table_p, g, lse_p)

    dw = pl.pallas_call(
        _dw_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(items, rows),
            in_specs=[
                pl.BlockSpec((tile, embed), lambda j, i, *_: (i, 0)),
                pl.BlockSpec((item_tile, embed), lambda j, i, *_: (j, 0)),
                pl.BlockSpec((tile, 1), lambda j, i, *_: (i, 0)),
                pl.BlockSpec((tile, 1), lambda j, i, *_: (i, 0)),
            ],
            out_specs=pl.BlockSpec((item_tile, embed), lambda j, i, *_: (j, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((items_pad, embed), jnp.float32),
        interpret=interpret,
    )(scalar, hidden_p, table_p, g, lse_p)

    return (
        dh[:n].astype(hidden.dtype),
        dw[:num_rows].astype(table.dtype),
        # num_valid is an int scalar: its cotangent is the symbolic float0 zero
        np.zeros(np.shape(num_valid), jax.dtypes.float0),
    )


_fused_lse.defvjp(_fused_lse_fwd, _fused_lse_bwd)
