from .distributed import initialize_distributed, replicas_info
from .launch import LaunchError, WorkerResult, clean_cpu_env, free_port, launch_workers
from .introspect import (
    collective_bytes,
    collective_inventory,
    sharding_report,
    summarize_collectives,
)
from .ring import full_attention_reference, ring_attention
from .sharded_ce import sharded_fused_lse
from .sharding import (
    LOGICAL_AXES,
    ShardingRules,
    ShardingRuleWarning,
    logical_axes,
    logical_axes_tree,
    params_shardings,
    shard_activation,
    sharding_scope,
)

__all__ = [
    "LOGICAL_AXES",
    "LaunchError",
    "ShardingRuleWarning",
    "ShardingRules",
    "WorkerResult",
    "clean_cpu_env",
    "collective_bytes",
    "collective_inventory",
    "free_port",
    "full_attention_reference",
    "initialize_distributed",
    "launch_workers",
    "logical_axes",
    "logical_axes_tree",
    "params_shardings",
    "replicas_info",
    "ring_attention",
    "shard_activation",
    "sharding_report",
    "sharded_fused_lse",
    "sharding_scope",
    "summarize_collectives",
]
