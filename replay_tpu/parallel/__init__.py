from .ring import full_attention_reference, ring_attention

__all__ = ["full_attention_reference", "ring_attention"]
