from .distributed import initialize_distributed, replicas_info
from .ring import full_attention_reference, ring_attention
from .sharded_ce import sharded_fused_lse

__all__ = [
    "full_attention_reference",
    "initialize_distributed",
    "replicas_info",
    "ring_attention",
    "sharded_fused_lse",
]
