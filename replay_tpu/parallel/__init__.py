from .distributed import initialize_distributed, replicas_info
from .introspect import (
    collective_bytes,
    collective_inventory,
    sharding_report,
    summarize_collectives,
)
from .ring import full_attention_reference, ring_attention
from .sharded_ce import sharded_fused_lse

__all__ = [
    "collective_bytes",
    "collective_inventory",
    "full_attention_reference",
    "initialize_distributed",
    "replicas_info",
    "ring_attention",
    "sharding_report",
    "sharded_fused_lse",
    "summarize_collectives",
]
