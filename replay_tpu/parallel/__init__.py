from .distributed import initialize_distributed, replicas_info
from .introspect import (
    collective_bytes,
    collective_inventory,
    sharding_report,
    summarize_collectives,
)
from .ring import full_attention_reference, ring_attention
from .sharded_ce import sharded_fused_lse
from .sharding import (
    LOGICAL_AXES,
    ShardingRules,
    ShardingRuleWarning,
    logical_axes,
    logical_axes_tree,
    params_shardings,
    shard_activation,
    sharding_scope,
)

__all__ = [
    "LOGICAL_AXES",
    "ShardingRuleWarning",
    "ShardingRules",
    "collective_bytes",
    "collective_inventory",
    "full_attention_reference",
    "initialize_distributed",
    "logical_axes",
    "logical_axes_tree",
    "params_shardings",
    "replicas_info",
    "ring_attention",
    "shard_activation",
    "sharding_report",
    "sharded_fused_lse",
    "sharding_scope",
    "summarize_collectives",
]
