"""Multi-host runtime initialization.

Capability parity with the reference's distributed seams (SURVEY.md §2.9/§5: a
``torch.distributed`` consumed read-only for rank/world_size, collectives
delegated to NCCL): here the whole backend is ``jax.distributed.initialize`` +
XLA collectives over ICI/DCN — one call per host process, then every
``Mesh``/``psum`` in the framework spans all hosts automatically.

``initialize_distributed()`` is idempotent, no-ops in single-process runs, and
resolves the coordinator from standard env vars (fleet schedulers set them):

* ``REPLAY_TPU_COORDINATOR`` / ``JAX_COORDINATOR_ADDRESS`` — host:port
* ``REPLAY_TPU_NUM_PROCESSES`` / ``JAX_NUM_PROCESSES``
* ``REPLAY_TPU_PROCESS_ID`` / ``JAX_PROCESS_ID``

On TPU pods jax can discover everything from the runtime, so calling with no
env set is also valid there.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger("replay_tpu")

_initialized = False


def _env(*names: str) -> Optional[str]:
    for name in names:
        value = os.environ.get(name)
        if value:
            return value
    return None


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> dict:
    """Join the multi-host job (idempotent). Returns the process layout."""
    global _initialized
    import jax

    coordinator_address = coordinator_address or _env(
        "REPLAY_TPU_COORDINATOR", "JAX_COORDINATOR_ADDRESS"
    )
    num_processes = num_processes or _int_env("REPLAY_TPU_NUM_PROCESSES", "JAX_NUM_PROCESSES")
    process_id = process_id if process_id is not None else _int_env(
        "REPLAY_TPU_PROCESS_ID", "JAX_PROCESS_ID"
    )

    # the flag marks an ACTUAL initialization: a no-op call (no coordinator, not
    # a pod) must not block a later call that does carry a coordinator
    if not _initialized and (coordinator_address is not None or _on_tpu_pod()):
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True
        logger.info(
            "joined distributed job: process %d/%d",
            jax.process_index(),
            jax.process_count(),
        )

    return {
        "process_id": jax.process_index(),
        "num_processes": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def _int_env(*names: str) -> Optional[int]:
    value = _env(*names)
    return int(value) if value is not None else None


def _on_tpu_pod() -> bool:
    """Heuristic: MULTI-worker TPU runtimes list several worker hostnames —
    single-host setups (including one-chip dev tunnels) must not initialize."""
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hostnames.split(",") if h.strip()]) > 1 and os.environ.get(
        "JAX_PLATFORMS", ""
    ) not in ("cpu",)


def replicas_info(num_workers: int = 1):
    """The input-sharding identity of this process (after initialization)."""
    from replay_tpu.data.nn.partitioning import ReplicasInfo

    return ReplicasInfo.from_jax(num_workers=num_workers)
