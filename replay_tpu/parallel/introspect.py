"""Collective + sharding introspection over lowered/compiled HLO.

Beyond-parity (the reference's DDP story has no cross-device observability at
all — SURVEY.md §2.9): the DP×TP(×SP) programs this stack compiles move bytes
through XLA-inserted collectives that no host-side tracer can see. This module
makes them inspectable *statically*, from the compiled program's HLO text —
no device execution, no profiler session:

* :func:`collective_inventory` walks an ``as_text()`` dump and returns every
  collective op (all-gather / all-reduce / reduce-scatter / collective-permute
  / all-to-all, ``-start`` async variants included) with its result shape,
  dtype, byte size and replica groups, plus a best-effort mesh-axis guess.
* :func:`summarize_collectives` folds an inventory into the
  ``{count, bytes, by_op}`` record carried by bench rows and dry runs.
* :func:`sharding_report` renders every param leaf's ``PartitionSpec`` and
  flags *accidental full replication* — a table that was supposed to shard
  over the mesh (``expect_sharded``) but lowered replicated, the silent way a
  vocab-TP run degenerates into n_tp copies of the catalog.

The HLO-text parsing half is import-light (pure ``re``); only
:func:`sharding_report` touches jax (lazily) to read leaf shardings. The
CEFusedTP no-table-gather regression guard (tests/parallel/test_collectives.py)
is built on :func:`collective_inventory`: PR 7's core invariant — the
``[I/n_tp, E]`` item table is never all-gathered, only the ``[rows]``-sized
lse/max combine moves over the TP axis — is now a static assertion, not a
memory graph someone eyeballs.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Mapping, Optional, Sequence

__all__ = [
    "collective_bytes",
    "collective_inventory",
    "sharding_report",
    "summarize_collectives",
]

# HLO element sizes in bytes (shape strings like f32[8,16]{1,0})
_DTYPE_BYTES = {
    "pred": 1,
    "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_COLLECTIVE_OPS = (
    "all-gather",
    "all-reduce",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
    "collective-broadcast",
)

# `%name = f32[8,16]{1,0} all-gather(...)` — the shape part is matched
# lazily up to the first op token because optimized-HLO layouts carry
# tiling/memory-space annotations (`{1,0:T(8,128)}`, `{1,0:S(1)}`) and async
# starts have tuple shapes; the op token itself is always the first thing
# after the result shape, so the lazy match cannot overshoot into operands
_COLLECTIVE_RE = re.compile(
    r"%?(?P<name>[\w.\-]+)\s*=\s*(?P<shape>.+?)\s"
    r"(?P<op>" + "|".join(_COLLECTIVE_OPS) + r")(?:-start)?\("
)

_SHAPE_RE = re.compile(r"(?P<dtype>[a-z]\w*?)\[(?P<dims>[\d,\s]*)\]")

_REPLICA_GROUPS_RE = re.compile(r"replica_groups=\{(?P<groups>[^}]*(?:\},\{[^}]*)*)\}\}")
# iota-form groups: replica_groups=[2,4]<=[4,2]T(1,0)
_IOTA_GROUPS_RE = re.compile(r"replica_groups=\[(?P<shape>[\d,]+)\]<=")


def _shape_bytes(shape_text: str) -> Optional[int]:
    """Total byte size of an HLO shape string (sum over tuple elements);
    None when no parseable array shape is present (token/opaque shapes)."""
    total = 0
    seen = False
    for match in _SHAPE_RE.finditer(shape_text):
        dtype = match.group("dtype")
        if dtype not in _DTYPE_BYTES:
            continue
        seen = True
        dims = [int(d) for d in match.group("dims").replace(" ", "").split(",") if d]
        count = 1
        for dim in dims:
            count *= dim
        total += count * _DTYPE_BYTES[dtype]
    return total if seen else None


def _parse_groups(line: str) -> Optional[List[List[int]]]:
    match = _REPLICA_GROUPS_RE.search(line)
    if match:
        groups = []
        for part in match.group("groups").split("},{"):
            ids = [int(x) for x in part.strip("{}").split(",") if x.strip()]
            if ids:
                groups.append(ids)
        return groups or None
    match = _IOTA_GROUPS_RE.search(line)
    if match:
        # iota form [G, S]<=[...]: G groups of S devices; synthesize the ids
        # only as sizes (the permutation is not worth re-deriving here)
        dims = [int(d) for d in match.group("shape").split(",")]
        if len(dims) == 2:
            return [list(range(start * dims[1], (start + 1) * dims[1])) for start in range(dims[0])]
    return None


def _axis_guess(groups: Optional[List[List[int]]], mesh_shape: Optional[Mapping[str, int]]) -> Optional[str]:
    """Best-effort mesh-axis attribution from replica-group stride.

    A ``("data", "model")`` mesh lays devices out row-major: groups of
    consecutive ids (stride 1) move over the LAST axis, groups with stride ==
    last-axis size move over the first. Returns None when the pattern matches
    neither (multi-axis collectives, permutes with custom pairs).
    """
    if not groups or not mesh_shape or len(mesh_shape) < 1:
        return None
    axes = list(mesh_shape.items())
    group = groups[0]
    if len(group) < 2:
        return None
    stride = group[1] - group[0]
    if any(b - a != stride for a, b in zip(group, group[1:])):
        return None
    # row-major layout: the last axis has stride 1; an axis earlier in the
    # tuple has stride == product of the later axes' sizes
    running = 1
    for name, size in reversed(axes):
        if stride == running and len(group) == size:
            return name
        running *= size
    return None


def collective_inventory(
    hlo_text: str, mesh_shape: Optional[Mapping[str, int]] = None
) -> List[Dict[str, Any]]:
    """Every collective op in an HLO ``as_text()`` dump.

    Returns one record per op: ``{"op", "name", "shape", "bytes",
    "replica_groups", "group_size", "mesh_axis"}``. ``bytes`` is the RESULT
    shape's size — the resident footprint the collective materializes (for an
    all-gather this is the gathered tensor, i.e. what the no-table-gather
    guard bounds); per-shard shapes in an SPMD module are per-device.
    ``mesh_axis`` is a best-effort stride guess against ``mesh_shape`` (e.g.
    ``{"data": 4, "model": 2}``), None when ambiguous. ``-done`` halves of
    async pairs are skipped — the ``-start`` op carries the shape.
    """
    inventory: List[Dict[str, Any]] = []
    for line in hlo_text.splitlines():
        if "-done(" in line:
            continue
        match = _COLLECTIVE_RE.search(line)
        if not match:
            continue
        groups = _parse_groups(line)
        record = {
            "op": match.group("op"),
            "name": match.group("name"),
            "shape": " ".join(match.group("shape").split()),
            "bytes": _shape_bytes(match.group("shape")),
            "replica_groups": groups,
            "group_size": len(groups[0]) if groups else None,
            "mesh_axis": _axis_guess(groups, mesh_shape),
        }
        inventory.append(record)
    return inventory


def collective_bytes(inventory: Sequence[Mapping[str, Any]]) -> int:
    """Total result bytes over an inventory (unparseable shapes count 0)."""
    return int(sum(entry.get("bytes") or 0 for entry in inventory))


def summarize_collectives(inventory: Sequence[Mapping[str, Any]]) -> Dict[str, Any]:
    """Fold an inventory into the record bench rows / dry runs carry:
    ``{"count", "bytes", "by_op": {op: {"count", "bytes"}}}``."""
    by_op: Dict[str, Dict[str, int]] = {}
    for entry in inventory:
        bucket = by_op.setdefault(str(entry.get("op")), {"count": 0, "bytes": 0})
        bucket["count"] += 1
        bucket["bytes"] += int(entry.get("bytes") or 0)
    return {
        "count": len(inventory),
        "bytes": collective_bytes(inventory),
        "by_op": by_op,
    }


def sharding_report(
    params: Any,
    mesh: Any = None,
    expect_sharded: Sequence[str] = ("embedding_",),
    rules: Any = None,
) -> Dict[str, Any]:
    """Render every param leaf's PartitionSpec; flag accidental replication.

    Returns ``{"params": [{"path", "shape", "spec", "bytes", "replicated"}],
    "replicated_bytes", "sharded_bytes", "flags": [...]}``. A leaf is
    *replicated* when its spec names no mesh axis. ``flags`` lists the
    failure modes a DP×TP(×SP) run must not ship silently:

    * with a :class:`~replay_tpu.parallel.sharding.ShardingRules` table in
      ``rules`` (the preferred mode): any leaf whose logical-axis annotation
      maps to a multi-device mesh axis under the table but lowered fully
      replicated — the rule said shard, the program did not. This is the
      "zero accidental full replication under the rules" check the dryrun and
      CI hard-assert. Leaves the rule table legitimately replicates (rule →
      None, or a non-divisible dim the placement already warned about) are
      never flagged.
    * without ``rules`` (legacy mode): a ≥2-D leaf whose path matches
      ``expect_sharded`` but lowered fully replicated on a multi-device
      ``model`` axis (the vocab-TP table degenerating into n_tp full copies);
    * any leaf with no readable sharding at all (host arrays that never got
      placed).
    """
    import jax

    model_axis_size = None
    if mesh is not None:
        try:
            model_axis_size = int(dict(mesh.shape).get("model", 1))
        except (TypeError, ValueError):
            model_axis_size = None

    expected_axes = None
    if rules is not None:
        if mesh is None:
            msg = "sharding_report(rules=...) needs the mesh to size the rules"
            raise ValueError(msg)
        from replay_tpu.parallel.sharding import logical_axes

        def rule_expectation(path, leaf):
            """Mesh axes the table wants for this leaf (divisible dims only —
            the same resolved_axis decision param placement made)."""
            names = logical_axes(path, leaf)
            shape = tuple(getattr(leaf, "shape", ()) or ())
            return tuple(
                rules.resolved_axis(mesh, name, dim)
                for name, dim in zip(names, shape)
            )

        expected_axes = rule_expectation

    table: List[Dict[str, Any]] = []
    flags: List[str] = []
    replicated_bytes = 0
    sharded_bytes = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        path_str = jax.tree_util.keystr(path)
        shape = tuple(getattr(leaf, "shape", ()) or ())
        nbytes = int(getattr(leaf, "nbytes", 0) or 0)
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        spec_str = str(spec) if spec is not None else None
        replicated = spec is None or not any(axis is not None for axis in tuple(spec))
        table.append(
            {
                "path": path_str,
                "shape": list(shape),
                "spec": spec_str,
                "bytes": nbytes,
                "replicated": bool(replicated),
            }
        )
        if replicated:
            replicated_bytes += nbytes
        else:
            sharded_bytes += nbytes
        if sharding is None:
            flags.append(f"{path_str}: no sharding readable (never placed?)")
        elif expected_axes is not None:
            wanted = expected_axes(path, leaf)
            if replicated and any(axis is not None for axis in wanted):
                flags.append(
                    f"{path_str}: fully replicated {list(shape)} but the rule "
                    f"table wants {wanted} (accidental replication)"
                )
        elif (
            replicated
            and len(shape) >= 2
            and model_axis_size
            and model_axis_size > 1
            and any(marker in path_str for marker in expect_sharded)
        ):
            flags.append(
                f"{path_str}: fully replicated {list(shape)} on an "
                f"n_tp={model_axis_size} mesh — expected a 'model'-sharded "
                "table (accidental replication)"
            )
    return {
        "params": table,
        "replicated_bytes": replicated_bytes,
        "sharded_bytes": sharded_bytes,
        "flags": flags,
    }
