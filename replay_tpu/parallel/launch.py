"""Process-real worker launching for multi-host training on one machine.

The rest of :mod:`replay_tpu.parallel` assumes ``jax.distributed`` has been
initialized; this module starts the actual OS processes. One launcher call
starts N python workers, each a real ``jax.distributed`` rank (gloo CPU
collectives under tests; the same worker scripts run unchanged on TPU pods
where the runtime provides the coordinator), and supervises them to
completion:

* **Coordinator handshake (no fixed ports):** the launcher binds an ephemeral
  port for the jax.distributed coordinator and publishes it to every worker
  via the standard env vars ``initialize_distributed`` already resolves
  (``REPLAY_TPU_COORDINATOR`` / ``REPLAY_TPU_NUM_PROCESSES`` /
  ``REPLAY_TPU_PROCESS_ID``) — two launchers on one host can never collide.
  The same address is also passed as argv for workers that predate the env
  contract.

* **Peer-death supervision:** collectives hang forever when a peer dies —
  a SIGKILLed rank leaves every survivor blocked inside gloo with no error.
  The launcher polls; once any worker exits (cleanly or by signal), the
  remaining workers get ``grace_s`` to finish on their own, then are
  SIGKILLed and reported with ``reaped=True``. A chaos test therefore always
  gets its processes back: the victim's real ``-SIGKILL`` returncode AND the
  survivors' reaped state, never a hung pytest.

* **No pipe deadlocks:** worker stdout/stderr spool to temp files (a worker
  logging megabytes can never fill a pipe and block mid-collective).

``launch_workers`` is the harness behind ``tests/parallel/test_multiprocess``
and the multi-process leg of ``__graft_entry__.dryrun_multichip``;
``clean_cpu_env`` builds the sanitized per-worker environment (no TPU-relay
sitecustomize, forced CPU platform, N virtual devices per process).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence

logger = logging.getLogger("replay_tpu")

__all__ = ["WorkerResult", "LaunchError", "free_port", "clean_cpu_env", "launch_workers"]


def free_port(host: str = "127.0.0.1") -> int:
    """An ephemeral port chosen by the OS — callers bind-and-release, then
    hand the number to a child that binds it for real. The tiny race this
    leaves is why every consumer here also tolerates a failed bind loudly."""
    with socket.socket() as sock:
        sock.bind((host, 0))
        return sock.getsockname()[1]


def clean_cpu_env(
    local_devices: int = 4,
    repo_root: Optional[str] = None,
    extra: Optional[Dict[str, str]] = None,
) -> Dict[str, str]:
    """A sanitized environment for a CPU worker process: the TPU-relay
    sitecustomize stripped (its PJRT registration serializes on the device
    grant and can block for minutes), the platform forced to CPU with
    ``local_devices`` virtual devices, and gloo selected for CPU collectives.
    """
    root = str(repo_root) if repo_root is not None else str(Path.cwd())
    env = {
        **{k: v for k, v in os.environ.items() if ".axon_site" not in v},
        "PYTHONPATH": root,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={local_devices}",
        "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo",
        "REPLAY_TPU_CLEAN_REEXEC": "1",
    }
    env.update(extra or {})
    return env


@dataclasses.dataclass
class WorkerResult:
    """One worker's outcome: its rank, how it exited, and what it printed.

    With ``launch_workers(run_dir=...)``, ``artifacts_dir`` names the rank's
    persisted forensic directory (``<run_dir>/workers/rank<i>/``: full
    stdout/stderr spools + ``meta.json`` on abnormal exit, and the worker's
    flight ring if it recorded one) and ``flight_path`` the ring path the
    worker was handed — ``None`` without a run_dir."""

    rank: int
    returncode: Optional[int]
    stdout: str
    stderr: str
    reaped: bool = False  # launcher had to SIGKILL it after a peer died/hung
    artifacts_dir: Optional[str] = None
    flight_path: Optional[str] = None

    @property
    def killed_by(self) -> Optional[int]:
        """The signal number that killed the worker, or ``None``."""
        if self.returncode is not None and self.returncode < 0:
            return -self.returncode
        return None


class LaunchError(RuntimeError):
    """Raised (``check=True``) when any worker exits nonzero or is reaped."""


def launch_workers(
    script: str,
    num_processes: int,
    args_for: Optional[Callable[[int], Sequence[str]]] = None,
    env: Optional[Dict[str, str]] = None,
    timeout: float = 300.0,
    grace_s: float = 20.0,
    check: bool = True,
    pass_rank_argv: bool = True,
    python: str = sys.executable,
    run_dir: Optional[str] = None,
) -> List[WorkerResult]:
    """Run ``num_processes`` copies of ``script`` as one distributed job.

    Each worker gets the coordinator handshake via env
    (``REPLAY_TPU_COORDINATOR``/``REPLAY_TPU_NUM_PROCESSES``/
    ``REPLAY_TPU_PROCESS_ID``) and — with ``pass_rank_argv`` — as leading
    argv ``<rank> <host:port>``, followed by ``args_for(rank)``.

    Supervision: after the first worker exit, survivors get ``grace_s``
    seconds (a peer's death wedges gloo collectives — waiting longer only
    hangs the caller), then are SIGKILLed with ``reaped=True``. ``timeout``
    bounds the whole job the same way. With ``check=True`` any nonzero or
    reaped worker raises :class:`LaunchError` carrying the stderr tails;
    chaos callers pass ``check=False`` and assert on the results directly.

    ``run_dir`` turns the launch forensic: every rank is handed a flight-ring
    path (``REPLAY_TPU_FLIGHT_PATH`` → ``<run_dir>/workers/rank<i>/
    flight.ring``, which ``Trainer.fit`` picks up with no worker change —
    the worker's last records survive its SIGKILL in the ring), and on
    abnormal exit (nonzero, signaled, or reaped) the rank's FULL stdout/
    stderr spools plus a ``meta.json`` (returncode, ``killed_by``, reaped)
    are persisted next to it — the artifacts CI uploads and
    ``obs.report --postmortem`` merges. A :class:`LaunchError` then names
    the persisted paths instead of only quoting stderr tails.
    """
    if num_processes < 1:
        msg = f"num_processes must be >= 1, got {num_processes}"
        raise ValueError(msg)
    coordinator = f"127.0.0.1:{free_port()}"
    base_env = dict(env if env is not None else os.environ)
    rank_dirs: List[Optional[Path]] = [None] * num_processes
    if run_dir is not None:
        for rank in range(num_processes):
            rank_dirs[rank] = Path(run_dir) / "workers" / f"rank{rank}"
            rank_dirs[rank].mkdir(parents=True, exist_ok=True)
    spools = []
    workers: List[subprocess.Popen] = []
    try:
        for rank in range(num_processes):
            worker_env = {
                **base_env,
                "REPLAY_TPU_COORDINATOR": coordinator,
                "REPLAY_TPU_NUM_PROCESSES": str(num_processes),
                "REPLAY_TPU_PROCESS_ID": str(rank),
            }
            if rank_dirs[rank] is not None:
                worker_env["REPLAY_TPU_FLIGHT_PATH"] = str(
                    rank_dirs[rank] / "flight.ring"
                )
            argv = [python, str(script)]
            if pass_rank_argv:
                argv += [str(rank), coordinator]
            argv += [str(a) for a in (args_for(rank) if args_for else ())]
            out = tempfile.TemporaryFile()
            err = tempfile.TemporaryFile()
            spools.append((out, err))
            workers.append(
                subprocess.Popen(argv, env=worker_env, stdout=out, stderr=err)
            )

        reaped = [False] * num_processes
        deadline = time.monotonic() + timeout
        first_exit_at: Optional[float] = None
        while any(w.poll() is None for w in workers):
            now = time.monotonic()
            exited = [w for w in workers if w.poll() is not None]
            if exited and first_exit_at is None:
                first_exit_at = now
            hung_past_grace = first_exit_at is not None and now - first_exit_at > grace_s
            if now > deadline or hung_past_grace:
                reason = "timeout" if now > deadline else (
                    f"peer exited {grace_s:.0f}s ago; collectives are wedged"
                )
                for rank, worker in enumerate(workers):
                    if worker.poll() is None:
                        logger.warning(
                            "launch_workers: reaping rank %d (%s)", rank, reason
                        )
                        worker.send_signal(signal.SIGKILL)
                        reaped[rank] = True
                for worker in workers:
                    worker.wait(timeout=30)
                break
            time.sleep(0.1)

        results = []
        for rank, (worker, (out, err)) in enumerate(zip(workers, spools)):
            worker.wait(timeout=30)
            out.seek(0)
            err.seek(0)
            rank_dir = rank_dirs[rank]
            result = WorkerResult(
                rank=rank,
                returncode=worker.returncode,
                stdout=out.read().decode(errors="replace"),
                stderr=err.read().decode(errors="replace"),
                reaped=reaped[rank],
            )
            if rank_dir is not None:
                result.flight_path = str(rank_dir / "flight.ring")
                if result.returncode != 0 or result.reaped:
                    result.artifacts_dir = str(
                        _persist_worker_artifacts(rank_dir, result)
                    )
            results.append(result)
    finally:
        for worker in workers:  # never leak a live worker past the call
            if worker.poll() is None:
                worker.kill()
                worker.wait(timeout=30)
        for out, err in spools:
            out.close()
            err.close()

    if check:
        bad = [r for r in results if r.returncode != 0 or r.reaped]
        if bad:
            details = "\n".join(
                f"rank {r.rank}: returncode={r.returncode} reaped={r.reaped}"
                + (f" artifacts={r.artifacts_dir}" if r.artifacts_dir else "")
                + f"\n{r.stderr[-2000:]}"
                for r in bad
            )
            msg = f"{len(bad)}/{num_processes} workers failed:\n{details}"
            raise LaunchError(msg)
    return results


def _persist_worker_artifacts(rank_dir: Path, result: WorkerResult) -> Path:
    """Write a dead worker's full spools + exit metadata into its rank dir.

    The in-memory :class:`WorkerResult` dies with the test process; CI (and
    ``obs.report --postmortem``) need the evidence on disk next to the flight
    ring. Full spools — the 2000-char stderr tail in :class:`LaunchError` is
    for humans reading an exception, not for forensics."""
    (rank_dir / "stdout.log").write_text(result.stdout, errors="replace")
    (rank_dir / "stderr.log").write_text(result.stderr, errors="replace")
    meta = {
        "rank": result.rank,
        "returncode": result.returncode,
        "killed_by": result.killed_by,
        "reaped": result.reaped,
    }
    (rank_dir / "meta.json").write_text(json.dumps(meta, indent=2) + "\n")
    return rank_dir
