"""Process-real worker launching for multi-host training on one machine.

The rest of :mod:`replay_tpu.parallel` assumes ``jax.distributed`` has been
initialized; this module starts the actual OS processes. One launcher call
starts N python workers, each a real ``jax.distributed`` rank (gloo CPU
collectives under tests; the same worker scripts run unchanged on TPU pods
where the runtime provides the coordinator), and supervises them to
completion:

* **Coordinator handshake (no fixed ports):** the launcher binds an ephemeral
  port for the jax.distributed coordinator and publishes it to every worker
  via the standard env vars ``initialize_distributed`` already resolves
  (``REPLAY_TPU_COORDINATOR`` / ``REPLAY_TPU_NUM_PROCESSES`` /
  ``REPLAY_TPU_PROCESS_ID``) — two launchers on one host can never collide.
  The same address is also passed as argv for workers that predate the env
  contract.

* **Peer-death supervision:** collectives hang forever when a peer dies —
  a SIGKILLed rank leaves every survivor blocked inside gloo with no error.
  The launcher polls; once any worker exits (cleanly or by signal), the
  remaining workers get ``grace_s`` to finish on their own, then are
  SIGKILLed and reported with ``reaped=True``. A chaos test therefore always
  gets its processes back: the victim's real ``-SIGKILL`` returncode AND the
  survivors' reaped state, never a hung pytest.

* **No pipe deadlocks:** worker stdout/stderr spool to temp files (a worker
  logging megabytes can never fill a pipe and block mid-collective).

``launch_workers`` is the harness behind ``tests/parallel/test_multiprocess``
and the multi-process leg of ``__graft_entry__.dryrun_multichip``;
``clean_cpu_env`` builds the sanitized per-worker environment (no TPU-relay
sitecustomize, forced CPU platform, N virtual devices per process).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence

logger = logging.getLogger("replay_tpu")

__all__ = ["WorkerResult", "LaunchError", "free_port", "clean_cpu_env", "launch_workers"]


def free_port(host: str = "127.0.0.1") -> int:
    """An ephemeral port chosen by the OS — callers bind-and-release, then
    hand the number to a child that binds it for real. The tiny race this
    leaves is why every consumer here also tolerates a failed bind loudly."""
    with socket.socket() as sock:
        sock.bind((host, 0))
        return sock.getsockname()[1]


def clean_cpu_env(
    local_devices: int = 4,
    repo_root: Optional[str] = None,
    extra: Optional[Dict[str, str]] = None,
) -> Dict[str, str]:
    """A sanitized environment for a CPU worker process: the TPU-relay
    sitecustomize stripped (its PJRT registration serializes on the device
    grant and can block for minutes), the platform forced to CPU with
    ``local_devices`` virtual devices, and gloo selected for CPU collectives.
    """
    root = str(repo_root) if repo_root is not None else str(Path.cwd())
    env = {
        **{k: v for k, v in os.environ.items() if ".axon_site" not in v},
        "PYTHONPATH": root,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={local_devices}",
        "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo",
        "REPLAY_TPU_CLEAN_REEXEC": "1",
    }
    env.update(extra or {})
    return env


@dataclasses.dataclass
class WorkerResult:
    """One worker's outcome: its rank, how it exited, and what it printed."""

    rank: int
    returncode: Optional[int]
    stdout: str
    stderr: str
    reaped: bool = False  # launcher had to SIGKILL it after a peer died/hung

    @property
    def killed_by(self) -> Optional[int]:
        """The signal number that killed the worker, or ``None``."""
        if self.returncode is not None and self.returncode < 0:
            return -self.returncode
        return None


class LaunchError(RuntimeError):
    """Raised (``check=True``) when any worker exits nonzero or is reaped."""


def launch_workers(
    script: str,
    num_processes: int,
    args_for: Optional[Callable[[int], Sequence[str]]] = None,
    env: Optional[Dict[str, str]] = None,
    timeout: float = 300.0,
    grace_s: float = 20.0,
    check: bool = True,
    pass_rank_argv: bool = True,
    python: str = sys.executable,
) -> List[WorkerResult]:
    """Run ``num_processes`` copies of ``script`` as one distributed job.

    Each worker gets the coordinator handshake via env
    (``REPLAY_TPU_COORDINATOR``/``REPLAY_TPU_NUM_PROCESSES``/
    ``REPLAY_TPU_PROCESS_ID``) and — with ``pass_rank_argv`` — as leading
    argv ``<rank> <host:port>``, followed by ``args_for(rank)``.

    Supervision: after the first worker exit, survivors get ``grace_s``
    seconds (a peer's death wedges gloo collectives — waiting longer only
    hangs the caller), then are SIGKILLed with ``reaped=True``. ``timeout``
    bounds the whole job the same way. With ``check=True`` any nonzero or
    reaped worker raises :class:`LaunchError` carrying the stderr tails;
    chaos callers pass ``check=False`` and assert on the results directly.
    """
    if num_processes < 1:
        msg = f"num_processes must be >= 1, got {num_processes}"
        raise ValueError(msg)
    coordinator = f"127.0.0.1:{free_port()}"
    base_env = dict(env if env is not None else os.environ)
    spools = []
    workers: List[subprocess.Popen] = []
    try:
        for rank in range(num_processes):
            worker_env = {
                **base_env,
                "REPLAY_TPU_COORDINATOR": coordinator,
                "REPLAY_TPU_NUM_PROCESSES": str(num_processes),
                "REPLAY_TPU_PROCESS_ID": str(rank),
            }
            argv = [python, str(script)]
            if pass_rank_argv:
                argv += [str(rank), coordinator]
            argv += [str(a) for a in (args_for(rank) if args_for else ())]
            out = tempfile.TemporaryFile()
            err = tempfile.TemporaryFile()
            spools.append((out, err))
            workers.append(
                subprocess.Popen(argv, env=worker_env, stdout=out, stderr=err)
            )

        reaped = [False] * num_processes
        deadline = time.monotonic() + timeout
        first_exit_at: Optional[float] = None
        while any(w.poll() is None for w in workers):
            now = time.monotonic()
            exited = [w for w in workers if w.poll() is not None]
            if exited and first_exit_at is None:
                first_exit_at = now
            hung_past_grace = first_exit_at is not None and now - first_exit_at > grace_s
            if now > deadline or hung_past_grace:
                reason = "timeout" if now > deadline else (
                    f"peer exited {grace_s:.0f}s ago; collectives are wedged"
                )
                for rank, worker in enumerate(workers):
                    if worker.poll() is None:
                        logger.warning(
                            "launch_workers: reaping rank %d (%s)", rank, reason
                        )
                        worker.send_signal(signal.SIGKILL)
                        reaped[rank] = True
                for worker in workers:
                    worker.wait(timeout=30)
                break
            time.sleep(0.1)

        results = []
        for rank, (worker, (out, err)) in enumerate(zip(workers, spools)):
            worker.wait(timeout=30)
            out.seek(0)
            err.seek(0)
            results.append(
                WorkerResult(
                    rank=rank,
                    returncode=worker.returncode,
                    stdout=out.read().decode(errors="replace"),
                    stderr=err.read().decode(errors="replace"),
                    reaped=reaped[rank],
                )
            )
    finally:
        for worker in workers:  # never leak a live worker past the call
            if worker.poll() is None:
                worker.kill()
                worker.wait(timeout=30)
        for out, err in spools:
            out.close()
            err.close()

    if check:
        bad = [r for r in results if r.returncode != 0 or r.reaped]
        if bad:
            details = "\n".join(
                f"rank {r.rank}: returncode={r.returncode} reaped={r.reaped}\n"
                f"{r.stderr[-2000:]}"
                for r in bad
            )
            msg = f"{len(bad)}/{num_processes} workers failed:\n{details}"
            raise LaunchError(msg)
    return results
