"""Ring attention: exact attention over sequences sharded across the mesh.

The reference framework has NO sequence parallelism (SURVEY.md §2.9 — session
lengths are managed by trimming/windowing). This module is the TPU-native
long-context extension the build plan calls first-class: sequences are sharded
over a mesh axis, and attention runs blockwise while key/value blocks rotate
around the ring with ``jax.lax.ppermute`` over ICI — memory per chip stays
O(L_local²-ish) and no all-gather of the full sequence ever materializes
(Ring Attention, arXiv 2310.01889; the pallas_guide.md collective pattern).

Numerics: an online-softmax accumulator (running max / denominator / weighted
sum — the flash-attention recurrence) makes the blockwise result exactly equal
to full softmax attention. Causality across blocks is resolved from ring
positions: the block held after ``s`` hops is the one ``s`` positions behind on
the ring, so a query block attends it fully when it is strictly earlier, with a
triangular mask when it is its own, and not at all when later.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_attention(q, k, v, bias, state):
    """One blockwise online-softmax update.

    q: [B, Lq, H, D]; k/v: [B, Lk, H, D]; bias: [B, 1, Lq, Lk]-broadcastable
    additive mask. state = (o [B, Lq, H, D], m [B, Lq, H], l [B, Lq, H]).
    """
    o, m, l = state
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    scores = scores + bias
    block_max = jnp.max(scores, axis=-1)  # [B, H, Lq]
    new_m = jnp.maximum(m, block_max.transpose(0, 2, 1))  # [B, Lq, H]
    correction = jnp.exp(m - new_m)
    probs = jnp.exp(scores - new_m.transpose(0, 2, 1)[:, :, :, None])  # [B, H, Lq, Lk]
    block_o = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    new_o = o * correction[..., None] + block_o
    new_l = l * correction + jnp.sum(probs, axis=-1).transpose(0, 2, 1)
    return new_o, new_m, new_l


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "sp",
    causal: bool = False,
    padding_mask: Optional[jnp.ndarray] = None,
    data_axis: Optional[str] = None,
) -> jnp.ndarray:
    """Exact multi-head attention with the sequence axis sharded over ``axis_name``.

    :param q, k, v: [B, L, H, D] GLOBAL arrays (sharded or to-be-sharded on L).
    :param padding_mask: optional [B, L] bool, True at real tokens.
    :param data_axis: mesh axis the BATCH dim stays sharded over (the DP×SP
        production layout — omitting it on a mesh whose batch is data-sharded
        would silently all-gather the batch into every ring shard).
    :return: [B, L, H, D] attention output, sharded like ``q``.
    """
    n_shards = mesh.shape[axis_name]
    if q.shape[1] % n_shards:
        msg = f"Sequence length {q.shape[1]} not divisible by {n_shards} ring shards"
        raise ValueError(msg)
    if data_axis is not None and q.shape[0] % mesh.shape[data_axis]:
        msg = (
            f"Batch {q.shape[0]} not divisible by the {mesh.shape[data_axis]}-way "
            f"{data_axis!r} axis"
        )
        raise ValueError(msg)
    local_len = q.shape[1] // n_shards

    def local_fn(q_blk, k_blk, v_blk, pad_blk):
        my_index = jax.lax.axis_index(axis_name)
        positions = jnp.arange(local_len)

        def make_bias(kv_owner, kv_pad):
            # additive mask for (my queries) x (kv_owner's keys): [B, 1, Lq, Lk]
            bias = jnp.zeros((local_len, local_len), q_blk.dtype)
            if causal:
                q_pos = my_index * local_len + positions[:, None]
                k_pos = kv_owner * local_len + positions[None, :]
                bias = jnp.where(k_pos <= q_pos, bias, NEG_INF)
            bias = bias[None, None, :, :]
            if kv_pad is not None:  # per-row key padding
                bias = bias + jnp.where(kv_pad, 0.0, NEG_INF)[:, None, None, :]
            return bias

        o = jnp.zeros_like(q_blk)
        m = jnp.full(q_blk.shape[:3], NEG_INF, q_blk.dtype)
        l = jnp.zeros(q_blk.shape[:3], q_blk.dtype)
        kv_k, kv_v, kv_pad = k_blk, v_blk, pad_blk
        perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
        for step in range(n_shards):
            kv_owner = (my_index - step) % n_shards
            bias = make_bias(kv_owner, kv_pad)
            o, m, l = _block_attention(q_blk, kv_k, kv_v, bias, (o, m, l))
            if step + 1 < n_shards:  # rotate kv one hop around the ring
                kv_k = jax.lax.ppermute(kv_k, axis_name, perm)
                kv_v = jax.lax.ppermute(kv_v, axis_name, perm)
                if kv_pad is not None:
                    kv_pad = jax.lax.ppermute(kv_pad, axis_name, perm)
        return o / jnp.maximum(l, 1e-30)[..., None]

    pad = padding_mask if padding_mask is not None else jnp.ones(q.shape[:2], bool)
    spec = P(data_axis, axis_name, None, None)
    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec, P(data_axis, axis_name)),
        out_specs=spec,
        check_rep=False,
    )(q, k, v, pad)


def full_attention_reference(q, k, v, causal=False, padding_mask=None):
    """Single-device full-softmax attention (the correctness oracle)."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    length = q.shape[1]
    if causal:
        tri = jnp.tril(jnp.ones((length, length), bool))
        scores = jnp.where(tri[None, None], scores, NEG_INF)
    if padding_mask is not None:
        scores = jnp.where(padding_mask[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
