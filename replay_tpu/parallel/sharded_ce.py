"""Vocab-sharded (tensor-parallel) fused catalog logsumexp.

Beyond-parity (SURVEY.md §2.9 TP row): the reference's CE head materializes
``[B, L, num_items]`` logits on ONE device (replay/nn/loss/ce.py:10) and has
no exact full-softmax story past that device's memory. The single-device
kernel (``replay_tpu.ops.fused_ce``) removes the ``[N, I]`` logits tensor
from HBM; this wrapper removes the ``[I, E]`` ITEM TABLE from the single
device. The table lives ``[I/n_tp, E]`` per chip over the mesh's
tensor-parallel axis (the same ``("model", None)`` layout
``Trainer(shard_vocab=True)`` places the embedding params in), each shard runs
the tile-wise online max/sum locally, and the shards combine with the two-pass
reduction

    m_g = pmax(lse_local)            s_g = psum(exp(lse_local − m_g))
    lse_g = m_g + log(s_g)

expressed as ``logsumexp(all_gather(lse_local))`` inside ``shard_map`` — the
all_gather moves ``n_tp`` scalars per row (nothing next to the table), and
unlike a raw ``pmax`` it is differentiable, so autodiff produces exactly the
backward the math wants: the cotangent reaching each shard is its softmax
share ``exp(lse_local − lse_g)``, ``dh`` is psummed across shards (the
transpose of the replicated-in ``hidden``), and ``dW`` stays shard-local (the
transpose of the sharded-in table).

Catalogs not divisible by ``n_tp`` are zero-padded to the shard grid and the
padding is masked INSIDE the kernel via its traced ``num_valid`` scalar
(each shard computes its own valid count from ``lax.axis_index``); a shard
that is entirely padding yields a finite ≈−1e30 lse whose contribution
underflows to exactly 0 in the combine (see ``ops/fused_ce._MASK``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from replay_tpu.ops.fused_ce import fused_lse

try:  # jax >= 0.4.35 re-homed shard_map; keep both import paths working
    from jax.experimental.shard_map import shard_map
except ImportError:  # pragma: no cover
    from jax.sharding import shard_map  # type: ignore[attr-defined]


def sharded_fused_lse(
    hidden: jnp.ndarray,
    table: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "model",
    data_axis: Optional[str] = "data",
    tile: int = 256,
    item_tile: Optional[int] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """``logsumexp(hidden @ table.T, axis=-1)`` with the catalog sharded over
    ``mesh``'s ``axis_name`` axis.

    :param hidden: ``[N, E]`` row vectors — sharded over ``data_axis`` when
        given (``N`` must divide by that axis size), replicated over
        ``axis_name``.
    :param table: ``[num_items, E]`` item embeddings (logically global; under
        ``shard_vocab`` the rows are already placed ``P(axis_name, None)`` and
        shard_map keeps them in place).
    :param data_axis: mesh axis the rows are data-parallel over — a single
        name, or a TUPLE of names for rows flattened from several sharded
        dims (the DP×SP fit's ``[B·L, E]`` hidden states stay sharded over
        ``("data", "seq")``; the trainer's rule table picks this). ``None``
        replicates the rows on every shard group (single-axis TP meshes).
    :return: ``[N]`` float32 log-sum-exp values, numerically equal to the
        replicated :func:`~replay_tpu.ops.fused_ce.fused_lse` up to the
        shard-combine's f32 reassociation.
    """
    if axis_name not in mesh.shape:
        msg = f"mesh {dict(mesh.shape)} has no {axis_name!r} axis to shard the catalog over"
        raise ValueError(msg)
    n_tp = mesh.shape[axis_name]
    num_items, _ = table.shape
    if data_axis is not None:
        row_axes = data_axis if isinstance(data_axis, tuple) else (data_axis,)
        n_data = 1
        for axis in row_axes:
            size = mesh.shape.get(axis)
            if size is None:
                msg = f"mesh {dict(mesh.shape)} has no {axis!r} axis for the rows"
                raise ValueError(msg)
            n_data *= size
        if hidden.shape[0] % n_data:
            msg = (
                f"sharded_fused_lse: {hidden.shape[0]} rows do not divide over "
                f"the {n_data}-way {data_axis!r} axes"
            )
            raise ValueError(msg)
    pad = -num_items % n_tp
    if pad:
        table = jnp.pad(table, ((0, pad), (0, 0)))
    shard_rows = (num_items + pad) // n_tp

    def body(h_block, w_shard):
        start = jax.lax.axis_index(axis_name) * shard_rows
        num_valid = jnp.clip(num_items - start, 0, shard_rows)
        lse_local = fused_lse(
            h_block, w_shard, tile, item_tile, interpret, num_valid=num_valid
        )
        # two-pass psum-style combine over the catalog shards: n_tp scalars
        # per row; differentiable (its VJP is each shard's softmax share)
        return jax.nn.logsumexp(jax.lax.all_gather(lse_local, axis_name), axis=0)

    row_spec = P(data_axis, None) if data_axis is not None else P(None, None)
    out_spec = P(data_axis) if data_axis is not None else P()
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(row_spec, P(axis_name, None)),
        out_specs=out_spec,
        # pallas_call has no replication rule; correctness is covered by the
        # parity tests on the virtual 8-device mesh (tests/ops)
        check_rep=False,
    )(hidden, table)
