"""One sharding-rule table: T5X-style logical-axis partitioning.

Beyond-parity (SURVEY.md §2.9; ROADMAP item 2): the reference has no sharding
story at all, and until this module the repo's own sharding was per-case
plumbing — ``make_mesh`` hardcoded a 2-axis grid, ``Trainer`` decided param
placement by string-matching ``"embedding_"`` in tree paths, and ``CEFusedTP``
carried its own ``shard_vocab`` layout. Following the T5X partitioning design
(SNIPPETS [3]), every array dimension now carries a *logical axis name* and ONE
:class:`ShardingRules` table maps logical names → mesh axes of the 3-axis
``("data", "model", "seq")`` mesh built by ``replay_tpu.nn.make_mesh``:

========  ====================================================================
logical   meaning
========  ====================================================================
batch     per-example rows of a batch (data parallelism)
length    sequence positions of an activation (sequence parallelism — the
          Ring Attention axis, arXiv 2310.01889)
vocab     item-catalog rows of an embedding table (vocab tensor parallelism —
          the CEFusedTP ``[I/n_tp, E]`` layout)
embed     the model width (residual stream)
heads     the fused attention head·head_dim projection width
mlp       the FFN hidden width
kv        per-head key/value width (reserved; fused into ``heads`` today)
position  rows of a positional table (NEVER sequence-sharded: positional rows
          are indexed by a python slice, not by activation position)
layers    the stacked-blocks axis of a ``scan_blocks`` encoder
========  ====================================================================

The default table maps ``batch → "data"``, ``length → "seq"``, ``vocab →
"model"`` (when vocab TP is on) and replicates everything else — exactly the
DP×TP×SP layout the dryrun and the ``sasrec_l1024`` bench family validate.
Params are annotated by :func:`logical_axes` — a declarative path→logical-name
table for the existing flax modules (the module-annotation equivalent T5X gets
from ``param_with_axes``) — so the trainer derives EVERY NamedSharding (params,
optimizer state, batches, activation constraints) from the one table, and
``parallel.introspect.sharding_report(rules=...)`` flags any leaf whose rule
wanted a mesh axis but lowered replicated.

A table row that cannot shard (row count not divisible by the mesh axis) warns
ONCE with the offending shape/axis and replicates that dimension — the silent
fallback the old ``_params_shardings`` shipped is now loud.
"""

from __future__ import annotations

import threading
import warnings
from contextlib import contextmanager
from dataclasses import dataclass, field, replace
from typing import Any, Dict, Mapping, Optional, Tuple, Union

__all__ = [
    "LOGICAL_AXES",
    "ShardingRuleWarning",
    "ShardingRules",
    "active_scope",
    "logical_axes",
    "logical_axes_tree",
    "params_shardings",
    "shard_activation",
    "sharding_scope",
]

MeshAxis = Union[None, str, Tuple[str, ...]]

LOGICAL_AXES = (
    "batch",
    "length",
    "vocab",
    "embed",
    "heads",
    "kv",
    "mlp",
    "position",
    "layers",
)


class ShardingRuleWarning(UserWarning):
    """A rule wanted to shard a dimension that cannot shard (falls back to
    replication for that dimension — loudly, once per offending leaf)."""


# ---------------------------------------------------------------------------
# the rule table
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class ShardingRules:
    """ONE logical-name → mesh-axis table driving every placement decision.

    ``rules`` maps each logical axis name to a mesh axis name, a tuple of mesh
    axis names (a dimension sharded over several axes, e.g. flattened
    ``[B·L, E]`` rows over ``("data", "seq")``), or ``None`` (replicated).
    Unknown logical names are an error at :meth:`spec` time — a typo must not
    silently replicate.
    """

    rules: Mapping[str, MeshAxis] = field(default_factory=dict)

    @classmethod
    def default(cls, shard_vocab: bool = False) -> "ShardingRules":
        """The DP×TP×SP production table: batch rows over ``data``, sequence
        positions over ``seq``, and (with ``shard_vocab``) catalog rows over
        ``model``. Everything else replicates — the vocab table is the only
        param big enough to earn TP today (docs/distributed_and_serving.md)."""
        return cls(
            rules={
                "batch": "data",
                "length": "seq",
                "vocab": "model" if shard_vocab else None,
                "embed": None,
                "heads": None,
                "kv": None,
                "mlp": None,
                "position": None,
                "layers": None,
            }
        )

    def with_rule(self, logical: str, mesh_axis: MeshAxis) -> "ShardingRules":
        """A copy with one rule overridden (rule tables are immutable)."""
        if logical not in LOGICAL_AXES:
            msg = f"unknown logical axis {logical!r}; known: {LOGICAL_AXES}"
            raise KeyError(msg)
        merged = dict(self.rules)
        merged[logical] = mesh_axis
        return replace(self, rules=merged)

    def mesh_axis(self, logical: str) -> MeshAxis:
        """The mesh axis (or tuple / None) a logical name maps to."""
        if logical is None:
            return None
        if logical not in LOGICAL_AXES:
            msg = f"unknown logical axis {logical!r}; known: {LOGICAL_AXES}"
            raise KeyError(msg)
        return self.rules.get(logical)

    def spec(self, *logical_names: Optional[str]):
        """A ``PartitionSpec`` for an array whose dims carry these names."""
        from jax.sharding import PartitionSpec as P

        return P(*(self.mesh_axis(name) for name in logical_names))

    def validate(self, mesh) -> None:
        """Every mapped mesh axis must exist on the mesh (typos fail loudly
        at trainer construction, not as a cryptic XLA error mid-fit)."""
        mesh_axes = set(dict(mesh.shape))
        for logical, target in self.rules.items():
            if logical not in LOGICAL_AXES:
                msg = f"unknown logical axis {logical!r}; known: {LOGICAL_AXES}"
                raise KeyError(msg)
            targets = target if isinstance(target, tuple) else (target,)
            for axis in targets:
                if axis is not None and axis not in mesh_axes:
                    msg = (
                        f"rule {logical!r} -> {target!r} names mesh axis "
                        f"{axis!r}, but the mesh has axes {sorted(mesh_axes)} "
                        "(build it with replay_tpu.nn.make_mesh)"
                    )
                    raise ValueError(msg)

    def axis_size(self, mesh, logical: str) -> int:
        """Product of the mesh-axis sizes a logical name shards over (1 when
        replicated)."""
        target = self.mesh_axis(logical)
        if target is None:
            return 1
        targets = target if isinstance(target, tuple) else (target,)
        size = 1
        for axis in targets:
            size *= int(mesh.shape[axis])
        return size

    def resolved_axis(self, mesh, logical: Optional[str], dim: int) -> MeshAxis:
        """The mesh axis (or tuple) a dimension of extent ``dim`` actually
        shards over under this table: the rule's target when it spans more
        than one device AND ``dim`` divides its total size, else ``None``
        (replicate). The ONE divisibility/triviality decision shared by param
        placement, activation constraints and the accidental-replication
        report."""
        target = self.mesh_axis(logical)
        if target is None:
            return None
        size = self.axis_size(mesh, logical)
        if size <= 1 or dim % size:
            return None
        return target

    def describe(self) -> Dict[str, Any]:
        """JSON-friendly view for run records / reports."""
        return {
            name: (list(axis) if isinstance(axis, tuple) else axis)
            for name, axis in self.rules.items()
        }


# ---------------------------------------------------------------------------
# the path → logical-name annotator (the module-annotation equivalent for the
# existing flax modules: one declarative table instead of per-module metadata)
# ---------------------------------------------------------------------------
# matched against the '/'-joined param path, FIRST match wins; each entry maps
# a (component-substring, leaf-name) pattern to the logical names of the
# TRAILING dims (a scan_blocks 'layers' dim is detected by ndim and prepended)
_PARAM_RULES: Tuple[Tuple[Tuple[str, ...], str, Tuple[str, ...]], ...] = (
    # per-feature vocab tables (SequenceEmbedding's embedding_<feature> scope,
    # CategoricalEmbedding/CategoricalListEmbedding nn.Embed) — the TP tables
    (("embedding_", "table"), "embedding", ("vocab", "embed")),
    # positional tables: indexed by a python slice over max_sequence_length,
    # so their row axis is 'position', never the sequence-sharded 'length'
    ((), "positional_embedding", ("position", "embed")),
    # Bert4Rec's learned <MASK> vector
    ((), "mask_embedding", ("embed",)),
    # attention projections: qkv kernels [embed, heads·head_dim], out kernel
    # [heads·head_dim, embed]
    (("attention", "out"), "kernel", ("heads", "embed")),
    (("attention", "out"), "bias", ("embed",)),
    (("attention",), "kernel", ("embed", "heads")),
    (("attention",), "bias", ("heads",)),
    # differential-attention lambda vectors live in per-head space
    (("attention",), "lambda_q1", ("heads",)),
    (("attention",), "lambda_k1", ("heads",)),
    (("attention",), "lambda_q2", ("heads",)),
    (("attention",), "lambda_k2", ("heads",)),
    # FFN: inner/gate/value kernels [embed, mlp], outer/out [mlp, embed]
    (("ffn", "outer"), "kernel", ("mlp", "embed")),
    (("ffn", "outer"), "bias", ("embed",)),
    (("ffn", "out"), "kernel", ("mlp", "embed")),
    (("ffn",), "kernel", ("embed", "mlp")),
    (("ffn",), "bias", ("mlp",)),
    # norms and generic projections live in the residual stream. A proj
    # kernel's INPUT dim gets no logical name: it is a stacked-feature /
    # tensor_dim axis (NumericalEmbedding, ConcatAggregator) — and naming it
    # "embed" too would build a duplicate-axis PartitionSpec the moment an
    # "embed" rule maps to a mesh axis
    ((), "scale", ("embed",)),
    (("norm",), "bias", ("embed",)),
    (("proj",), "kernel", (None, "embed")),
    (("proj",), "bias", ("embed",)),
)


def _path_components(path: Any) -> Tuple[str, ...]:
    """Normalize a jax key path (or a pre-joined string) to components."""
    if isinstance(path, str):
        return tuple(part for part in path.replace("'", "").replace("[", "/").replace("]", "").split("/") if part)
    import jax

    return tuple(
        part
        for part in jax.tree_util.keystr(path).replace("'", "").replace("[", "/").replace("]", "").split("/")
        if part
    )


def logical_axes(path: Any, leaf: Any) -> Tuple[Optional[str], ...]:
    """Logical axis names for one param leaf, from the declarative table.

    Unmatched leaves get all-``None`` names (replicated under any rules) —
    annotation coverage is reported, never guessed from shapes. A leaf whose
    ndim exceeds its pattern by one (a ``scan_blocks`` stacked encoder) gets
    ``"layers"`` prepended.
    """
    ndim = len(getattr(leaf, "shape", ()) or ())
    components = _path_components(path)
    leaf_name = components[-1] if components else ""
    scope = components[:-1]
    for markers, name, axes in _PARAM_RULES:
        if name != leaf_name:
            continue
        if not all(any(marker in part for part in scope) for marker in markers):
            continue
        if ndim == len(axes):
            return axes
        if ndim == len(axes) + 1:  # nn.scan-stacked blocks: [layers, ...]
            return ("layers",) + axes
        continue  # shape disagrees with the pattern: keep looking
    return (None,) * ndim


def logical_axes_tree(params: Any) -> Any:
    """The logical-axis annotation for every leaf of a param pytree."""
    import jax

    return jax.tree_util.tree_map_with_path(logical_axes, params)


# one warning per offending (path, axis) per process: the non-divisible
# fallback must be loud, not spammy — tests reset via _reset_rule_warnings()
_WARNED: set = set()
_WARNED_LOCK = threading.Lock()


def _reset_rule_warnings() -> None:
    with _WARNED_LOCK:
        _WARNED.clear()


def _resolved_dim_axis(
    mesh, rules: ShardingRules, logical: Optional[str], dim: int, path_str: str
) -> MeshAxis:
    """:meth:`ShardingRules.resolved_axis`, plus the one-time
    ShardingRuleWarning when the fallback was a DIVISIBILITY failure (a rule
    that wanted to shard but could not) rather than a trivial axis."""
    resolved = rules.resolved_axis(mesh, logical, dim)
    if resolved is not None:
        return resolved
    target = rules.mesh_axis(logical)
    if target is None:
        return None
    size = rules.axis_size(mesh, logical)
    if size > 1 and dim % size:
        key = (path_str, logical, target, dim)
        with _WARNED_LOCK:
            seen = key in _WARNED
            _WARNED.add(key)
        if not seen:
            targets = target if isinstance(target, tuple) else (target,)
            warnings.warn(
                f"sharding rule {logical!r} -> {target!r}: {path_str} has "
                f"{dim} rows, not divisible by the {size}-way "
                f"{'×'.join(targets)} mesh axis — REPLICATING this dimension "
                "instead (pad the table or change the rule)",
                ShardingRuleWarning,
                stacklevel=3,
            )
    return None


def params_shardings(mesh, params: Any, rules: ShardingRules) -> Any:
    """NamedShardings for a param pytree, derived from the rule table.

    Replaces the old path-string heuristic: every leaf is annotated by
    :func:`logical_axes` and placed by the ONE table. Non-divisible dims warn
    once (:class:`ShardingRuleWarning`) and replicate.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def place(path, leaf) -> NamedSharding:
        names = logical_axes(path, leaf)
        path_str = jax.tree_util.keystr(path)
        shape = tuple(getattr(leaf, "shape", ()) or ())
        resolved = tuple(
            _resolved_dim_axis(mesh, rules, name, dim, path_str)
            for name, dim in zip(names, shape)
        )
        return NamedSharding(mesh, P(*resolved))

    return jax.tree_util.tree_map_with_path(place, params)


# ---------------------------------------------------------------------------
# activation scope: the trainer installs (rules, mesh) while tracing its
# programs; model bodies call shard_activation(...) and the ring-attention
# route reads the mesh + seq axis from here (flax modules stay mesh-free)
# ---------------------------------------------------------------------------
_SCOPE = threading.local()


@contextmanager
def sharding_scope(rules: ShardingRules, mesh):
    """Install the (rules, mesh) pair for the duration of a program trace."""
    previous = getattr(_SCOPE, "value", None)
    _SCOPE.value = (rules, mesh)
    try:
        yield
    finally:
        _SCOPE.value = previous


def active_scope() -> Optional[Tuple[ShardingRules, Any]]:
    """The installed (rules, mesh), or None outside any trainer program."""
    return getattr(_SCOPE, "value", None)


def shard_activation(x, *logical_names: Optional[str]):
    """``with_sharding_constraint`` from the rule table; identity when no
    scope is installed (direct ``model.apply`` outside a trainer) or when
    every resolved axis is trivial. Non-divisible dims silently relax to
    replicated — activations are shaped by the batcher, and a short final
    batch must not warn per step.
    """
    scope = active_scope()
    if scope is None:
        return x
    rules, mesh = scope
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if len(logical_names) != x.ndim:
        msg = (
            f"shard_activation: {len(logical_names)} logical names for a "
            f"{x.ndim}-d activation {tuple(x.shape)}"
        )
        raise ValueError(msg)
    resolved = [
        rules.resolved_axis(mesh, name, dim)
        for name, dim in zip(logical_names, x.shape)
    ]
    if not any(axis is not None for axis in resolved):
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*resolved))
    )
