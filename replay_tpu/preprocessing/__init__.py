from .data_preparator import DataPreparator
from .padder import Padder
from .sequence_generator import SequenceGenerator
from .converter import CSRConverter
from .discretizer import (
    Discretizer,
    QuantileDiscretizingRule,
    UniformDiscretizingRule,
)
from .filters import (
    ConsecutiveDuplicatesFilter,
    EntityDaysFilter,
    GlobalDaysFilter,
    InteractionEntriesFilter,
    LowRatingFilter,
    MinCountFilter,
    NumInteractionsFilter,
    QuantileItemsFilter,
    TimePeriodFilter,
)
from .history_based_fp import EmptyFeatureProcessor, HistoryBasedFeaturesProcessor
from .label_encoder import (
    LabelEncoder,
    LabelEncoderPartialFitWarning,
    LabelEncoderTransformWarning,
    LabelEncodingRule,
    SequenceEncodingRule,
)
from .sessionizer import Sessionizer

__all__ = [
    "DataPreparator",
    "SequenceGenerator",
    "Padder",
    "CSRConverter",
    "ConsecutiveDuplicatesFilter",
    "Discretizer",
    "EmptyFeatureProcessor",
    "EntityDaysFilter",
    "GlobalDaysFilter",
    "HistoryBasedFeaturesProcessor",
    "InteractionEntriesFilter",
    "LabelEncoder",
    "LabelEncoderPartialFitWarning",
    "LabelEncoderTransformWarning",
    "LabelEncodingRule",
    "LowRatingFilter",
    "MinCountFilter",
    "NumInteractionsFilter",
    "QuantileDiscretizingRule",
    "QuantileItemsFilter",
    "SequenceEncodingRule",
    "Sessionizer",
    "TimePeriodFilter",
    "UniformDiscretizingRule",
]
