from .filters import (
    ConsecutiveDuplicatesFilter,
    EntityDaysFilter,
    GlobalDaysFilter,
    InteractionEntriesFilter,
    LowRatingFilter,
    MinCountFilter,
    NumInteractionsFilter,
    QuantileItemsFilter,
    TimePeriodFilter,
)
from .label_encoder import (
    LabelEncoder,
    LabelEncoderPartialFitWarning,
    LabelEncoderTransformWarning,
    LabelEncodingRule,
    SequenceEncodingRule,
)

__all__ = [
    "ConsecutiveDuplicatesFilter",
    "EntityDaysFilter",
    "GlobalDaysFilter",
    "InteractionEntriesFilter",
    "LabelEncoder",
    "LabelEncoderPartialFitWarning",
    "LabelEncoderTransformWarning",
    "LabelEncodingRule",
    "LowRatingFilter",
    "MinCountFilter",
    "NumInteractionsFilter",
    "QuantileItemsFilter",
    "SequenceEncodingRule",
    "TimePeriodFilter",
]
