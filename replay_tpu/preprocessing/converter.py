"""Interactions → sparse CSR matrix.

Capability parity with replay/preprocessing/converter.py:10 (CSRConverter:
data/row/column source columns, optional explicit matrix extent, duplicate
aggregation). Output is ``scipy.sparse.csr_matrix`` — the standard host-side
sparse interchange format (e.g. for SLIM/ItemKNN-style solvers or export)."""

from __future__ import annotations

from typing import Optional

import numpy as np
import pandas as pd


class CSRConverter:
    """Interactions frame -> ``scipy.sparse.csr_matrix`` (ref preprocessing/converter.py).

    >>> import pandas as pd
    >>> log = pd.DataFrame({"query_id": [0, 0, 1], "item_id": [0, 2, 1]})
    >>> CSRConverter().transform(log).toarray().tolist()
    [[1.0, 0.0, 1.0], [0.0, 1.0, 0.0]]
    """

    def __init__(
        self,
        first_dim_column: str = "query_id",
        second_dim_column: str = "item_id",
        data_column: Optional[str] = None,
        row_count: Optional[int] = None,
        column_count: Optional[int] = None,
        allow_collect_to_master: bool = True,  # accepted for API parity; pandas is host-side
    ) -> None:
        self.first_dim_column = first_dim_column
        self.second_dim_column = second_dim_column
        self.data_column = data_column
        self.row_count = row_count
        self.column_count = column_count

    def transform(self, interactions: pd.DataFrame):
        from scipy.sparse import csr_matrix

        rows = interactions[self.first_dim_column].to_numpy()
        cols = interactions[self.second_dim_column].to_numpy()
        if not np.issubdtype(rows.dtype, np.integer) or not np.issubdtype(cols.dtype, np.integer):
            msg = "CSRConverter requires integer-encoded id columns (run LabelEncoder first)."
            raise ValueError(msg)
        data = (
            interactions[self.data_column].to_numpy(np.float64)
            if self.data_column
            else np.ones(len(interactions))
        )
        shape = (
            self.row_count if self.row_count is not None else int(rows.max()) + 1,
            self.column_count if self.column_count is not None else int(cols.max()) + 1,
        )
        if (rows >= shape[0]).any() or (cols >= shape[1]).any():
            msg = "Ids exceed the requested matrix extent."
            raise ValueError(msg)
        matrix = csr_matrix((data, (rows, cols)), shape=shape)
        matrix.sum_duplicates()
        return matrix
