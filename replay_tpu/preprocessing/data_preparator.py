"""Raw-data intake: files or frames → the canonical interaction-log layout.

Capability parity with the reference
``replay/experimental/preprocessing/data_preparator.py:406`` (``DataPreparator``),
pandas-native. One call reads a file (csv/parquet/json) or takes a frame,
validates a ``columns_mapping``, renames to the canonical column names
(``query_id/item_id/timestamp/rating`` here — the reference's
``user_id/…/relevance``), fills absent log columns with defaults, and coerces
timestamp/rating dtypes. A mapping holding both ``query_id`` and ``item_id``
marks an interactions log; a single one marks a query/item feature frame
(no column generation or coercion beyond the rename).
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

import pandas as pd

LOG_COLUMNS = ("query_id", "item_id", "timestamp", "rating")

logger = logging.getLogger("replay_tpu")


class DataPreparator:
    """Normalize arbitrary raw frames/files into the library format.

    >>> raw = pd.DataFrame({"user": [2, 2, 1], "movie": [1, 2, 3], "rel": [5, 4, 3]})
    >>> out = DataPreparator().transform(
    ...     columns_mapping={"query_id": "user", "item_id": "movie", "rating": "rel"},
    ...     data=raw,
    ... )
    >>> sorted(out.columns)
    ['item_id', 'query_id', 'rating', 'timestamp']
    """

    DEFAULT_RATING = 1.0
    DEFAULT_TIMESTAMP = "2099-01-01"

    @staticmethod
    def read_as_pandas_df(
        data: Optional[pd.DataFrame] = None,
        path: Optional[str] = None,
        format_type: Optional[str] = None,
        **reader_kwargs,
    ) -> pd.DataFrame:
        """Read ``path`` as ``format_type`` (csv/parquet/json) or pass ``data`` through."""
        if data is not None:
            if hasattr(data, "to_pandas"):  # pragma: no cover - polars
                return data.to_pandas()
            if hasattr(data, "toPandas"):  # pragma: no cover - spark
                return data.toPandas()
            return data
        if path:
            readers = {
                "csv": pd.read_csv,
                "parquet": pd.read_parquet,
                "json": pd.read_json,
            }
            if format_type is None:
                suffix = str(path).rsplit(".", 1)[-1].lower()
                if suffix not in readers:
                    msg = (
                        f"format_type not given and extension {suffix!r} of {path!r} "
                        f"is not one of {sorted(readers)}"
                    )
                    raise ValueError(msg)
                format_type = suffix
            if format_type not in readers:
                msg = f"Invalid value of format_type='{format_type}'"
                raise ValueError(msg)
            return readers[format_type](path, **reader_kwargs)
        msg = "Either data or path parameters must not be None"
        raise ValueError(msg)

    def check_df(self, dataframe: pd.DataFrame, columns_mapping: Dict[str, str]) -> None:
        """Validate emptiness + mapping presence; log nulls and absent log columns."""
        if len(dataframe) == 0:
            msg = "DataFrame is empty"
            raise ValueError(msg)
        unknown = set(columns_mapping) - set(LOG_COLUMNS)
        if unknown:
            msg = f"Unknown columns_mapping keys {sorted(unknown)}; valid keys: {list(LOG_COLUMNS)}"
            raise ValueError(msg)
        for column in columns_mapping.values():
            if column not in dataframe.columns:
                msg = f"Column `{column}` stated in mapping is absent in dataframe"
                raise ValueError(msg)
        for column in columns_mapping.values():
            if dataframe[column].isna().any():
                logger.info(
                    "Column `%s` has NULL values. Handle NULL values before "
                    "the next data preprocessing/model training steps",
                    column,
                )
        if "query_id" in columns_mapping and "item_id" in columns_mapping:
            absent = set(LOG_COLUMNS) - set(columns_mapping)
            if absent:
                logger.info(
                    "Columns %s are absent and will be generated with default values",
                    sorted(absent),
                )
            rating_col = columns_mapping.get("rating")
            if rating_col is not None and not pd.api.types.is_numeric_dtype(
                dataframe[rating_col]
            ):
                logger.info(
                    "Rating column `%s` should be numeric, but it is %s",
                    rating_col,
                    dataframe[rating_col].dtype,
                )

    @classmethod
    def add_absent_log_cols(
        cls,
        dataframe: pd.DataFrame,
        columns_mapping: Dict[str, str],
        default_rating: float = DEFAULT_RATING,
        default_ts: str = DEFAULT_TIMESTAMP,
    ) -> pd.DataFrame:
        """Add defaulted ``rating`` / ``timestamp`` columns when unmapped."""
        out = dataframe
        absent = set(LOG_COLUMNS) - set(columns_mapping)
        if "rating" in absent:
            out = out.assign(rating=float(default_rating))
        if "timestamp" in absent:
            out = out.assign(timestamp=pd.Timestamp(default_ts))
        return out

    @staticmethod
    def _rename(df: pd.DataFrame, mapping: Dict[str, str]) -> pd.DataFrame:
        renames = {in_col: out_col for out_col, in_col in mapping.items() if in_col in df.columns}
        return df.rename(columns=renames)

    def transform(
        self,
        columns_mapping: Dict[str, str],
        data: Optional[pd.DataFrame] = None,
        path: Optional[str] = None,
        format_type: Optional[str] = None,
        date_format: Optional[str] = None,
        reader_kwargs: Optional[dict] = None,
    ) -> pd.DataFrame:
        """Read → check → rename → (logs only) fill defaults + coerce dtypes."""
        dataframe = self.read_as_pandas_df(
            data=data, path=path, format_type=format_type, **(reader_kwargs or {})
        )
        self.check_df(dataframe, columns_mapping)
        dataframe = self._rename(dataframe, columns_mapping)
        is_log = "query_id" in columns_mapping and "item_id" in columns_mapping
        if is_log:
            dataframe = self.add_absent_log_cols(dataframe, columns_mapping)
            if not pd.api.types.is_datetime64_any_dtype(dataframe["timestamp"]):
                if pd.api.types.is_numeric_dtype(dataframe["timestamp"]):
                    pass  # numeric epochs are first-class here (TPU-side ints)
                else:
                    dataframe = dataframe.assign(
                        timestamp=pd.to_datetime(dataframe["timestamp"], format=date_format)
                    )
            dataframe = dataframe.assign(rating=dataframe["rating"].astype(float))
        return dataframe.reset_index(drop=True)
