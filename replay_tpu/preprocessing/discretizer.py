"""Numerical-column discretization.

Capability parity with replay/preprocessing/discretizer.py:603 (Discretizer with
per-column rules): quantile and uniform binning rules with fit / partial-config /
transform / save-load, NaN passthrough or dedicated bucket, and a bin count that
collapses gracefully when a column has fewer distinct values than requested bins.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import List, Optional, Sequence

import numpy as np
import pandas as pd

HANDLE_INVALID = ("error", "skip", "keep")


class BaseDiscretizingRule:
    """One column's binning: fit edges, transform values to bucket ids."""

    def __init__(self, column: str, n_bins: int = 10, handle_invalid: str = "error") -> None:
        if n_bins < 2:
            msg = "n_bins must be >= 2"
            raise ValueError(msg)
        if handle_invalid not in HANDLE_INVALID:
            msg = f"handle_invalid must be one of {HANDLE_INVALID}"
            raise ValueError(msg)
        self.column = column
        self.n_bins = n_bins
        self.handle_invalid = handle_invalid
        self.bin_edges: Optional[np.ndarray] = None

    def _compute_edges(self, values: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def fit(self, df: pd.DataFrame) -> "BaseDiscretizingRule":
        values = df[self.column].dropna().to_numpy(np.float64)
        if len(values) == 0:
            msg = f"Column '{self.column}' has no non-NaN values to fit on."
            raise ValueError(msg)
        edges = np.unique(self._compute_edges(values))
        if len(edges) < 2:
            edges = np.array([values.min(), values.max() + 1e-9])
        self.bin_edges = edges
        return self

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        if self.bin_edges is None:
            msg = f"Rule for '{self.column}' is not fitted."
            raise RuntimeError(msg)
        values = df[self.column].to_numpy(np.float64)
        invalid = np.isnan(values)
        if invalid.any() and self.handle_invalid == "error":
            msg = f"Column '{self.column}' contains NaN and handle_invalid='error'."
            raise ValueError(msg)
        buckets = np.clip(
            np.searchsorted(self.bin_edges, values, side="right") - 1,
            0,
            len(self.bin_edges) - 2,
        )
        out = df.copy()
        if self.handle_invalid == "keep":
            # NaNs get their own trailing bucket
            buckets = np.where(invalid, len(self.bin_edges) - 1, buckets)
            out[self.column] = buckets.astype(np.int64)
        else:  # skip: leave NaN as NaN
            result = buckets.astype(np.float64)
            result[invalid] = np.nan
            out[self.column] = result if invalid.any() else buckets.astype(np.int64)
        return out

    def fit_transform(self, df: pd.DataFrame) -> pd.DataFrame:
        return self.fit(df).transform(df)

    def partial_fit(self, df: pd.DataFrame) -> "BaseDiscretizingRule":
        """Fit if unfitted; refitting bin edges incrementally is not supported
        (the reference's exact contract, discretizer.py:241-252)."""
        if self.bin_edges is None:
            return self.fit(df)
        msg = f"{type(self).__name__} is not implemented for partial_fit yet."
        raise NotImplementedError(msg)

    def set_handle_invalid(self, handle_invalid: str) -> None:
        """Switch the NaN strategy post-construction (ref discretizer.py:294)."""
        if handle_invalid not in HANDLE_INVALID:
            msg = f"handle_invalid must be one of {HANDLE_INVALID}"
            raise ValueError(msg)
        self.handle_invalid = handle_invalid

    def _as_dict(self) -> dict:
        return {
            "_rule": type(self).__name__,
            "column": self.column,
            "n_bins": self.n_bins,
            "handle_invalid": self.handle_invalid,
            "bin_edges": self.bin_edges.tolist() if self.bin_edges is not None else None,
        }


class QuantileDiscretizingRule(BaseDiscretizingRule):
    """Equal-frequency bins (quantile edges)."""

    def _compute_edges(self, values: np.ndarray) -> np.ndarray:
        return np.quantile(values, np.linspace(0, 1, self.n_bins + 1))


class UniformDiscretizingRule(BaseDiscretizingRule):
    """Equal-width bins over [min, max]."""

    def _compute_edges(self, values: np.ndarray) -> np.ndarray:
        return np.linspace(values.min(), values.max(), self.n_bins + 1)


_RULES = {cls.__name__: cls for cls in (QuantileDiscretizingRule, UniformDiscretizingRule)}


class Discretizer:
    """Apply a set of discretizing rules column-wise (ref Discretizer API).

    >>> import pandas as pd
    >>> df = pd.DataFrame({"age": [1.0, 2.0, 3.0, 4.0]})
    >>> Discretizer([QuantileDiscretizingRule("age", n_bins=2)]).fit_transform(df)[
    ...     "age"].tolist()
    [0, 0, 1, 1]
    """

    def __init__(self, rules: Sequence[BaseDiscretizingRule]) -> None:
        self.rules: List[BaseDiscretizingRule] = list(rules)

    def fit(self, df: pd.DataFrame) -> "Discretizer":
        for rule in self.rules:
            rule.fit(df)
        return self

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        for rule in self.rules:
            df = rule.transform(df)
        return df

    def fit_transform(self, df: pd.DataFrame) -> pd.DataFrame:
        return self.fit(df).transform(df)

    def partial_fit(self, df: pd.DataFrame) -> "Discretizer":
        """Delegate to each rule's partial_fit (fit-if-unfitted contract)."""
        for rule in self.rules:
            rule.partial_fit(df)
        return self

    def set_handle_invalid(self, handle_invalid: str) -> None:
        for rule in self.rules:
            rule.set_handle_invalid(handle_invalid)

    def save(self, path: str) -> None:
        target = Path(path).with_suffix(".replay")
        target.mkdir(parents=True, exist_ok=True)
        payload = {"_class_name": "Discretizer", "rules": [r._as_dict() for r in self.rules]}
        (target / "init_args.json").write_text(json.dumps(payload))

    @classmethod
    def load(cls, path: str) -> "Discretizer":
        source = Path(path).with_suffix(".replay")
        payload = json.loads((source / "init_args.json").read_text())
        rules = []
        for spec in payload["rules"]:
            rule = _RULES[spec["_rule"]](
                spec["column"], n_bins=spec["n_bins"], handle_invalid=spec["handle_invalid"]
            )
            if spec["bin_edges"] is not None:
                rule.bin_edges = np.asarray(spec["bin_edges"])
            rules.append(rule)
        return cls(rules)
