"""Interaction-log filters.

Capability parity with the reference filter set (replay/preprocessing/filters.py:57-1075):
InteractionEntriesFilter, MinCountFilter, LowRatingFilter, NumInteractionsFilter,
EntityDaysFilter, GlobalDaysFilter, TimePeriodFilter, QuantileItemsFilter,
ConsecutiveDuplicatesFilter. Pandas-first vectorized implementations.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from datetime import datetime, timedelta
from typing import Literal, Optional, Union

import numpy as np
import pandas as pd


class _BaseFilter(ABC):
    """A filter maps an interactions dataframe to a filtered dataframe."""

    def transform(self, interactions: pd.DataFrame) -> pd.DataFrame:
        return self._filter(interactions)

    @abstractmethod
    def _filter(self, interactions: pd.DataFrame) -> pd.DataFrame: ...


class InteractionEntriesFilter(_BaseFilter):
    """Iteratively drop users/items whose interaction counts fall outside given bounds.

    Applies user and item constraints alternately until a fixed point, the same
    convergence loop the reference runs (replay/preprocessing/filters.py:131-208).
    """

    def __init__(
        self,
        query_column: str = "user_id",
        item_column: str = "item_id",
        min_inter_per_user: Optional[int] = None,
        max_inter_per_user: Optional[int] = None,
        min_inter_per_item: Optional[int] = None,
        max_inter_per_item: Optional[int] = None,
        allow_caching: bool = True,
    ) -> None:
        self.query_column = query_column
        self.item_column = item_column
        self.min_inter_per_user = min_inter_per_user
        self.max_inter_per_user = max_inter_per_user
        self.min_inter_per_item = min_inter_per_item
        self.max_inter_per_item = max_inter_per_item
        self.allow_caching = allow_caching
        self.total_dropped_interactions = 0
        for lo, hi in ((min_inter_per_user, max_inter_per_user), (min_inter_per_item, max_inter_per_item)):
            if lo is not None and lo <= 0:
                msg = "minimum interaction bounds must be positive"
                raise ValueError(msg)
            if lo is not None and hi is not None and hi < lo:
                msg = "maximum interaction bound must be >= the minimum bound"
                raise ValueError(msg)

    def _filter(self, interactions: pd.DataFrame) -> pd.DataFrame:
        df = interactions
        while True:
            before = len(df)
            df = self._bound(df, self.query_column, self.min_inter_per_user, self.max_inter_per_user)
            df = self._bound(df, self.item_column, self.min_inter_per_item, self.max_inter_per_item)
            if len(df) == before or df.empty:
                break
        self.total_dropped_interactions = len(interactions) - len(df)
        return df

    @staticmethod
    def _bound(df: pd.DataFrame, column: str, lo: Optional[int], hi: Optional[int]) -> pd.DataFrame:
        if lo is None and hi is None:
            return df
        counts = df.groupby(column)[column].transform("size")
        mask = pd.Series(True, index=df.index)
        if lo is not None:
            mask &= counts >= lo
        if hi is not None:
            mask &= counts <= hi
        return df[mask]


class MinCountFilter(_BaseFilter):
    """Keep rows whose ``groupby_column`` value occurs at least ``num_entries`` times.

    >>> import pandas as pd
    >>> log = pd.DataFrame({"user_id": [1, 1, 2], "item_id": [10, 11, 10]})
    >>> MinCountFilter(num_entries=2).transform(log)
       user_id  item_id
    0        1       10
    1        1       11
    """

    def __init__(self, num_entries: int, groupby_column: str = "user_id") -> None:
        if num_entries <= 0:
            msg = "num_entries must be positive"
            raise ValueError(msg)
        self.num_entries = num_entries
        self.groupby_column = groupby_column

    def _filter(self, interactions: pd.DataFrame) -> pd.DataFrame:
        counts = interactions.groupby(self.groupby_column)[self.groupby_column].transform("size")
        return interactions[counts >= self.num_entries]


class LowRatingFilter(_BaseFilter):
    """Keep rows with ``rating_column`` >= ``value``.

    >>> import pandas as pd
    >>> log = pd.DataFrame({"item_id": [1, 2, 3], "rating": [1.0, 3.0, 5.0]})
    >>> LowRatingFilter(value=3.0).transform(log)["item_id"].tolist()
    [2, 3]
    """

    def __init__(self, value: float, rating_column: str = "rating") -> None:
        self.value = value
        self.rating_column = rating_column

    def _filter(self, interactions: pd.DataFrame) -> pd.DataFrame:
        return interactions[interactions[self.rating_column] >= self.value]


class NumInteractionsFilter(_BaseFilter):
    """Keep the first/last ``num_interactions`` interactions of each query (by timestamp).

    >>> import pandas as pd
    >>> log = pd.DataFrame({"user_id": [1, 1, 1], "item_id": [10, 11, 12],
    ...                     "timestamp": [0, 1, 2]})
    >>> NumInteractionsFilter(num_interactions=2, first=False).transform(log)[
    ...     "item_id"].tolist()
    [11, 12]
    """

    def __init__(
        self,
        num_interactions: int = 10,
        first: bool = True,
        query_column: str = "user_id",
        timestamp_column: str = "timestamp",
        item_column: Optional[str] = None,
    ) -> None:
        if num_interactions < 0:
            msg = "num_interactions must be non-negative"
            raise ValueError(msg)
        self.num_interactions = num_interactions
        self.first = first
        self.query_column = query_column
        self.timestamp_column = timestamp_column
        self.item_column = item_column

    def _filter(self, interactions: pd.DataFrame) -> pd.DataFrame:
        sort_cols = [self.timestamp_column] + ([self.item_column] if self.item_column else [])
        ordered = interactions.sort_values(sort_cols, ascending=self.first, kind="stable")
        kept = ordered.groupby(self.query_column, sort=False).head(self.num_interactions)
        return kept.sort_index()


class EntityDaysFilter(_BaseFilter):
    """Keep each entity's first/last ``days`` days of interactions."""

    def __init__(
        self,
        days: int = 10,
        first: bool = True,
        entity_column: str = "user_id",
        timestamp_column: str = "timestamp",
    ) -> None:
        if days <= 0:
            msg = "days must be positive"
            raise ValueError(msg)
        self.days = days
        self.first = first
        self.entity_column = entity_column
        self.timestamp_column = timestamp_column

    def _filter(self, interactions: pd.DataFrame) -> pd.DataFrame:
        ts = pd.to_datetime(interactions[self.timestamp_column])
        window = pd.Timedelta(days=self.days)
        if self.first:
            start = ts.groupby(interactions[self.entity_column]).transform("min")
            mask = ts < start + window
        else:
            end = ts.groupby(interactions[self.entity_column]).transform("max")
            mask = ts > end - window
        return interactions[mask]


class GlobalDaysFilter(_BaseFilter):
    """Keep the dataset's first/last ``days`` days of interactions."""

    def __init__(self, days: int = 10, first: bool = True, timestamp_column: str = "timestamp") -> None:
        if days <= 0:
            msg = "days must be positive"
            raise ValueError(msg)
        self.days = days
        self.first = first
        self.timestamp_column = timestamp_column

    def _filter(self, interactions: pd.DataFrame) -> pd.DataFrame:
        ts = pd.to_datetime(interactions[self.timestamp_column])
        window = pd.Timedelta(days=self.days)
        if self.first:
            return interactions[ts < ts.min() + window]
        return interactions[ts > ts.max() - window]


class TimePeriodFilter(_BaseFilter):
    """Keep interactions inside ``[start_date, end_date)``."""

    def __init__(
        self,
        start_date: Union[str, datetime, None] = None,
        end_date: Union[str, datetime, None] = None,
        timestamp_column: str = "timestamp",
        time_column_format: str = "%Y-%m-%d %H:%M:%S",
    ) -> None:
        self.start_date = self._parse(start_date, time_column_format)
        self.end_date = self._parse(end_date, time_column_format)
        self.timestamp_column = timestamp_column

    @staticmethod
    def _parse(date: Union[str, datetime, None], fmt: str) -> Optional[datetime]:
        return datetime.strptime(date, fmt) if isinstance(date, str) else date

    def _filter(self, interactions: pd.DataFrame) -> pd.DataFrame:
        ts = pd.to_datetime(interactions[self.timestamp_column])
        mask = pd.Series(True, index=interactions.index)
        if self.start_date is not None:
            mask &= ts >= self.start_date
        if self.end_date is not None:
            mask &= ts < self.end_date
        return interactions[mask]


class QuantileItemsFilter(_BaseFilter):
    """Undersample over-popular items above the ``alpha_quantile`` of item counts.

    For every item whose count exceeds the quantile threshold, removes
    ``items_proportion`` of the excess over the long-tail maximum, taking rows from
    the most-active users first (reference: replay/preprocessing/filters.py:833-995).
    """

    def __init__(
        self,
        alpha_quantile: float = 0.99,
        items_proportion: float = 0.5,
        query_column: str = "query_id",
        item_column: str = "item_id",
    ) -> None:
        if not 0 < alpha_quantile < 1:
            msg = "alpha_quantile must be in (0, 1)"
            raise ValueError(msg)
        if not 0 < items_proportion < 1:
            msg = "items_proportion must be in (0, 1)"
            raise ValueError(msg)
        self.alpha_quantile = alpha_quantile
        self.items_proportion = items_proportion
        self.query_column = query_column
        self.item_column = item_column

    def _filter(self, interactions: pd.DataFrame) -> pd.DataFrame:
        item_counts = interactions.groupby(self.item_column)[self.item_column].transform("size")
        user_counts = interactions.groupby(self.query_column)[self.query_column].transform("size")
        per_item_counts = interactions.groupby(self.item_column).size()
        threshold = per_item_counts.quantile(self.alpha_quantile, interpolation="midpoint")

        long_tail_mask = item_counts <= threshold
        long_tail_max = item_counts[long_tail_mask].max() if long_tail_mask.any() else 0
        head = interactions[~long_tail_mask].copy()
        if head.empty:
            return interactions
        head["__n_del"] = (self.items_proportion * (item_counts[~long_tail_mask] - long_tail_max)).astype(int)
        head["__ucount"] = user_counts[~long_tail_mask]
        head = head.sort_values("__ucount", ascending=False, kind="stable")

        rank = head.groupby(self.item_column).cumcount()
        keep_head = head[rank >= head["__n_del"]]
        result = pd.concat([interactions[long_tail_mask], keep_head[interactions.columns]])
        return result

class ConsecutiveDuplicatesFilter(_BaseFilter):
    """Collapse runs of repeated items inside each query's timeline to one row.

    >>> import pandas as pd
    >>> log = pd.DataFrame({
    ...     "query_id": [1, 1, 1, 1], "item_id": [10, 10, 11, 10],
    ...     "timestamp": [0, 1, 2, 3],
    ... })
    >>> ConsecutiveDuplicatesFilter().transform(log)["item_id"].tolist()
    [10, 11, 10]
    """

    def __init__(
        self,
        keep: Literal["first", "last"] = "first",
        query_column: str = "query_id",
        item_column: str = "item_id",
        timestamp_column: str = "timestamp",
    ) -> None:
        if keep not in ("first", "last"):
            msg = "keep must be 'first' or 'last'"
            raise ValueError(msg)
        self.keep = keep
        self.query_column = query_column
        self.item_column = item_column
        self.timestamp_column = timestamp_column

    def _filter(self, interactions: pd.DataFrame) -> pd.DataFrame:
        shift = 1 if self.keep == "first" else -1
        ordered = interactions.sort_values(self.timestamp_column, kind="stable")
        neighbor = ordered.groupby(self.query_column)[self.item_column].shift(shift)
        return ordered[ordered[self.item_column] != neighbor].reset_index(drop=True)
