"""History-based feature generation for two-stage rerankers.

Capability parity with replay/preprocessing/history_based_fp.py:381
(HistoryBasedFeaturesProcessor: log-derived query/item statistics + conditional
popularity features over chosen categorical columns). All aggregations are
vectorized pandas groupbys; fit stores the feature frames, transform joins them
onto (query, item) candidate pairs — the second-stage feature-enrichment step of
the reference's TwoStages scenario.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import pandas as pd


class EmptyFeatureProcessor:
    """No-op stand-in (the reference uses it when a side has no features)."""

    def fit(self, *_args, **_kwargs) -> "EmptyFeatureProcessor":
        return self

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        return df


class HistoryBasedFeaturesProcessor:
    """Log-derived query/item statistic features.

    Query side: interaction count, distinct items, mean/std rating, history span
    and recency. Item side: interaction count, distinct queries, mean/std rating,
    popularity share. Conditional popularity: for each column in
    ``query_cat_features_list`` / ``item_cat_features_list``, the share of the
    query's (item's) history falling into each category value.
    """

    def __init__(
        self,
        use_log_features: bool = True,
        use_conditional_popularity: bool = True,
        query_cat_features_list: Optional[Sequence[str]] = None,
        item_cat_features_list: Optional[Sequence[str]] = None,
        query_column: str = "query_id",
        item_column: str = "item_id",
        rating_column: str = "rating",
        timestamp_column: str = "timestamp",
    ) -> None:
        self.use_log_features = use_log_features
        self.use_conditional_popularity = use_conditional_popularity
        self.query_cat_features_list = list(query_cat_features_list or [])
        self.item_cat_features_list = list(item_cat_features_list or [])
        self.query_column = query_column
        self.item_column = item_column
        self.rating_column = rating_column
        self.timestamp_column = timestamp_column
        self.query_features: Optional[pd.DataFrame] = None
        self.item_features: Optional[pd.DataFrame] = None
        self.conditional_features: List[pd.DataFrame] = []
        self.fitted = False

    def _log_features(self, log: pd.DataFrame) -> None:
        has_rating = self.rating_column in log.columns
        has_ts = self.timestamp_column in log.columns
        q = log.groupby(self.query_column).agg(
            q_log_count=(self.item_column, "size"),
            q_distinct_items=(self.item_column, "nunique"),
        )
        i = log.groupby(self.item_column).agg(
            i_log_count=(self.query_column, "size"),
            i_distinct_queries=(self.query_column, "nunique"),
        )
        if has_rating:
            q[["q_mean_rating", "q_std_rating"]] = log.groupby(self.query_column)[
                self.rating_column
            ].agg(["mean", "std"])
            i[["i_mean_rating", "i_std_rating"]] = log.groupby(self.item_column)[
                self.rating_column
            ].agg(["mean", "std"])
        if has_ts:
            ts = log[self.timestamp_column]
            latest = ts.max()
            spans = log.groupby(self.query_column)[self.timestamp_column].agg(["min", "max"])
            q["q_history_span"] = _seconds(spans["max"] - spans["min"])
            q["q_recency"] = _seconds(latest - spans["max"])
        i["i_popularity_share"] = i["i_log_count"] / len(log)
        self.query_features = q.fillna(0.0).reset_index()
        self.item_features = i.fillna(0.0).reset_index()

    def _conditional(self, log: pd.DataFrame, query_features, item_features) -> None:
        self.conditional_features = []
        if item_features is not None:
            for column in self.item_cat_features_list:
                joined = log.merge(
                    item_features[[self.item_column, column]], on=self.item_column, how="left"
                )
                share = (
                    joined.groupby([self.query_column, column])
                    .size()
                    .rename("share")
                    .reset_index()
                )
                totals = share.groupby(self.query_column)["share"].transform("sum")
                share["share"] /= totals
                wide = share.pivot_table(
                    index=self.query_column, columns=column, values="share", fill_value=0.0
                )
                wide.columns = [f"q_share_{column}_{value}" for value in wide.columns]
                self.conditional_features.append(
                    ("query", wide.reset_index())
                )
        if query_features is not None:
            for column in self.query_cat_features_list:
                joined = log.merge(
                    query_features[[self.query_column, column]], on=self.query_column, how="left"
                )
                share = (
                    joined.groupby([self.item_column, column])
                    .size()
                    .rename("share")
                    .reset_index()
                )
                totals = share.groupby(self.item_column)["share"].transform("sum")
                share["share"] /= totals
                wide = share.pivot_table(
                    index=self.item_column, columns=column, values="share", fill_value=0.0
                )
                wide.columns = [f"i_share_{column}_{value}" for value in wide.columns]
                self.conditional_features.append(("item", wide.reset_index()))

    def fit(
        self,
        log: pd.DataFrame,
        query_features: Optional[pd.DataFrame] = None,
        item_features: Optional[pd.DataFrame] = None,
    ) -> "HistoryBasedFeaturesProcessor":
        if self.use_log_features:
            self._log_features(log)
        if self.use_conditional_popularity:
            self._conditional(log, query_features, item_features)
        self.fitted = True
        return self

    def transform(self, pairs: pd.DataFrame) -> pd.DataFrame:
        """Join the fitted features onto (query, item) candidate pairs."""
        if not self.fitted:
            msg = "HistoryBasedFeaturesProcessor is not fitted."
            raise RuntimeError(msg)
        out = pairs
        if self.query_features is not None:
            out = out.merge(self.query_features, on=self.query_column, how="left")
        if self.item_features is not None:
            out = out.merge(self.item_features, on=self.item_column, how="left")
        for side, frame in self.conditional_features:
            key = self.query_column if side == "query" else self.item_column
            out = out.merge(frame, on=key, how="left")
        feature_columns = [c for c in out.columns if c not in pairs.columns]
        return out.fillna({c: 0.0 for c in feature_columns})


def _seconds(delta):
    if hasattr(delta, "dt"):
        return delta.dt.total_seconds()
    return delta.astype(np.float64)
