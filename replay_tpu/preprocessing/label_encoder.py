"""Categorical label encoding into contiguous integer ids.

Capability parity with the reference encoder (replay/preprocessing/label_encoder.py:86-996):
per-column ``LabelEncodingRule`` with fit / partial_fit / transform / inverse_transform,
unknown-label strategies ``error`` / ``use_default_value`` / ``drop`` (default value may be
``"last"``), ``SequenceEncodingRule`` for list columns, and a ``LabelEncoder`` composing
several rules. Pandas-first implementation on numpy factorization instead of the
reference's triple-backend join pipelines.
"""

from __future__ import annotations

import warnings
from collections.abc import Mapping, Sequence
from typing import Literal, Optional, Union

import numpy as np
import pandas as pd

from replay_tpu.utils.serde import to_plain

HandleUnknownStrategies = Literal["error", "use_default_value", "drop"]
_STRATEGIES = ("error", "use_default_value", "drop")


class LabelEncoderTransformWarning(Warning):
    """Warning raised on lossy transform behavior (dropping unknown labels)."""


class LabelEncoderPartialFitWarning(Warning):
    """Warning raised when partial_fit is called before fit."""


class LabelEncodingRule:
    """Encode one scalar column's values into contiguous ids ``[0, n)``.

    >>> import pandas as pd
    >>> rule = LabelEncodingRule("item_id", handle_unknown="use_default_value",
    ...                          default_value=-1)
    >>> _ = rule.fit(pd.DataFrame({"item_id": ["a", "b"]}))
    >>> rule.transform(pd.DataFrame({"item_id": ["b", "NEW"]}))["item_id"].tolist()
    [1, -1]
    """

    def __init__(
        self,
        column: str,
        mapping: Optional[Mapping] = None,
        handle_unknown: HandleUnknownStrategies = "error",
        default_value: Union[int, str, None] = None,
    ) -> None:
        if handle_unknown not in _STRATEGIES:
            msg = f"handle_unknown must be one of {_STRATEGIES}, got {handle_unknown!r}."
            raise ValueError(msg)
        if (
            handle_unknown == "use_default_value"
            and default_value is not None
            and not isinstance(default_value, int)
            and default_value != "last"
        ):
            msg = "default_value must be int, None or 'last'."
            raise ValueError(msg)
        self._column = column
        self._handle_unknown = handle_unknown
        self._default_value = default_value
        self._mapping: Optional[dict] = dict(mapping) if mapping is not None else None
        self._inverse: Optional[list] = None

    # -- properties -------------------------------------------------------
    @property
    def column(self) -> str:
        return self._column

    @property
    def is_fitted(self) -> bool:
        return self._mapping is not None

    def get_mapping(self) -> Mapping:
        self._require_fitted()
        return self._mapping

    def get_inverse_mapping(self) -> Mapping:
        self._require_fitted()
        return {code: label for label, code in self._mapping.items()}

    def _require_fitted(self):
        if self._mapping is None:
            msg = f"LabelEncodingRule for '{self._column}' is not fitted."
            raise RuntimeError(msg)

    def _inverse_list(self) -> list:
        if self._inverse is None or len(self._inverse) != len(self._mapping):
            self._inverse = [None] * len(self._mapping)
            for label, code in self._mapping.items():
                self._inverse[code] = label
        return self._inverse

    # -- column value access (overridden by SequenceEncodingRule) ---------
    def _values(self, df: pd.DataFrame) -> np.ndarray:
        return df[self._column].to_numpy()

    def _flat_values(self, df: pd.DataFrame) -> np.ndarray:
        return self._values(df)

    # -- fitting ----------------------------------------------------------
    def fit(self, df: pd.DataFrame) -> "LabelEncodingRule":
        values = pd.unique(pd.Series(self._flat_values(df)))
        self._mapping = {label: code for code, label in enumerate(values)}
        self._inverse = None
        if self._handle_unknown == "use_default_value" and self._default_value in self._mapping:
            warnings.warn(
                f"default_value {self._default_value} collides with an encoded label.",
                LabelEncoderTransformWarning,
                stacklevel=2,
            )
        return self

    def partial_fit(self, df: pd.DataFrame) -> "LabelEncodingRule":
        if self._mapping is None:
            warnings.warn(
                "partial_fit called before fit; falling back to fit.",
                LabelEncoderPartialFitWarning,
                stacklevel=2,
            )
            return self.fit(df)
        next_code = len(self._mapping)
        for label in pd.unique(pd.Series(self._flat_values(df))):
            if label not in self._mapping:
                self._mapping[label] = next_code
                next_code += 1
        self._inverse = None
        return self

    # -- encoding ---------------------------------------------------------
    def _encode_array(self, values: np.ndarray) -> np.ndarray:
        codes = pd.Series(values).map(self._mapping)
        unknown = codes.isna().to_numpy()
        if unknown.any():
            if self._handle_unknown == "error":
                unknown_labels = pd.unique(pd.Series(values)[unknown])
                msg = f"Found unknown labels in column '{self._column}': {list(unknown_labels)[:10]}"
                raise ValueError(msg)
            default = len(self._mapping) if self._default_value == "last" else self._default_value
            if default is None:
                default = -1
            codes = codes.fillna(default)
        return codes.to_numpy(dtype=np.int64), unknown

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        self._require_fitted()
        result = df.copy()
        codes, unknown = self._encode_array(self._values(df))
        result[self._column] = codes
        if unknown.any() and self._handle_unknown == "drop":
            result = result.loc[~unknown]
            if result.empty:
                warnings.warn(
                    f"Transform of column '{self._column}' with handle_unknown='drop' "
                    "produced an empty dataframe.",
                    LabelEncoderTransformWarning,
                    stacklevel=2,
                )
        return result

    def inverse_transform(self, df: pd.DataFrame) -> pd.DataFrame:
        self._require_fitted()
        inverse = self._inverse_list()
        result = df.copy()
        result[self._column] = pd.Series(df[self._column].to_numpy()).map(
            lambda c: inverse[c] if 0 <= c < len(inverse) else None
        ).to_numpy()
        return result

    # -- configuration ----------------------------------------------------
    def set_default_value(self, default_value: Union[int, str, None]) -> None:
        if default_value is not None and not isinstance(default_value, int) and default_value != "last":
            msg = "default_value must be int, None or 'last'."
            raise ValueError(msg)
        self._default_value = default_value

    def set_handle_unknown(self, handle_unknown: HandleUnknownStrategies) -> None:
        if handle_unknown not in _STRATEGIES:
            msg = f"handle_unknown must be one of {_STRATEGIES}, got {handle_unknown!r}."
            raise ValueError(msg)
        self._handle_unknown = handle_unknown


    # -- persistence -------------------------------------------------------
    def _as_dict(self) -> dict:
        return {
            "_rule": type(self).__name__,
            "column": self._column,
            "handle_unknown": self._handle_unknown,
            "default_value": self._default_value,
            "mapping": [
                [to_plain(label), int(code)] for label, code in self._mapping.items()
            ]
            if self._mapping is not None
            else None,
        }

    @classmethod
    def _from_dict(cls, data: dict) -> "LabelEncodingRule":
        rule_cls = _RULE_CLASSES[data["_rule"]]
        return rule_cls(
            data["column"],
            mapping={label: code for label, code in data["mapping"]}
            if data["mapping"] is not None
            else None,
            handle_unknown=data["handle_unknown"],
            default_value=data["default_value"],
        )

    def save(self, path: str) -> None:
        """One rule as a ``.replay`` artifact (ref label_encoder.py:508)."""
        import json
        from pathlib import Path

        target = Path(path).with_suffix(".replay")
        target.mkdir(parents=True, exist_ok=True)
        (target / "init_args.json").write_text(json.dumps(self._as_dict()))

    @classmethod
    def load(cls, path: str) -> "LabelEncodingRule":
        import json
        from pathlib import Path

        source = Path(path).with_suffix(".replay")
        return cls._from_dict(json.loads((source / "init_args.json").read_text()))



class SequenceEncodingRule(LabelEncodingRule):
    """Encode a list-typed column element-wise with one shared mapping."""

    def _flat_values(self, df: pd.DataFrame) -> np.ndarray:
        return df[self._column].explode().dropna().to_numpy()

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        self._require_fitted()
        result = df.copy()
        mapping = self._mapping
        unknown_found = [False]
        handle = self._handle_unknown
        default = len(mapping) if self._default_value == "last" else self._default_value

        def encode_seq(seq):
            out = []
            for label in seq:
                code = mapping.get(label)
                if code is None:
                    unknown_found[0] = True
                    if handle == "error":
                        msg = f"Found unknown label {label!r} in list column '{self._column}'"
                        raise ValueError(msg)
                    if handle == "drop":
                        continue
                    out.append(default if default is not None else -1)
                else:
                    out.append(code)
            return np.asarray(out, dtype=np.int64)

        result[self._column] = df[self._column].map(encode_seq)
        if unknown_found[0] and handle == "drop":
            lengths = result[self._column].map(len)
            if (lengths == 0).all():
                warnings.warn(
                    f"Transform of list column '{self._column}' with handle_unknown='drop' "
                    "dropped every element.",
                    LabelEncoderTransformWarning,
                    stacklevel=2,
                )
        return result

    def inverse_transform(self, df: pd.DataFrame) -> pd.DataFrame:
        self._require_fitted()
        inverse = self._inverse_list()
        result = df.copy()
        result[self._column] = df[self._column].map(
            lambda seq: np.asarray(
                [inverse[c] if 0 <= c < len(inverse) else None for c in seq], dtype=object
            )
        )
        return result


class LabelEncoder:
    """Apply a set of encoding rules column-wise to a dataframe.

    >>> import pandas as pd
    >>> log = pd.DataFrame({"item_id": ["b", "a", "b"]})
    >>> encoder = LabelEncoder([LabelEncodingRule("item_id")])
    >>> encoder.fit_transform(log)["item_id"].tolist()
    [0, 1, 0]
    >>> encoder.inverse_transform(pd.DataFrame({"item_id": [1]}))["item_id"].tolist()
    ['a']
    """

    def __init__(self, rules: Sequence[LabelEncodingRule]) -> None:
        self.rules = list(rules)

    @property
    def mapping(self) -> Mapping[str, Mapping]:
        return {rule.column: rule.get_mapping() for rule in self.rules}

    @property
    def inverse_mapping(self) -> Mapping[str, Mapping]:
        return {rule.column: rule.get_inverse_mapping() for rule in self.rules}

    def fit(self, df: pd.DataFrame) -> "LabelEncoder":
        for rule in self.rules:
            rule.fit(df)
        return self

    def partial_fit(self, df: pd.DataFrame) -> "LabelEncoder":
        for rule in self.rules:
            rule.partial_fit(df)
        return self

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        for rule in self.rules:
            df = rule.transform(df)
        return df

    def inverse_transform(self, df: pd.DataFrame) -> pd.DataFrame:
        for rule in self.rules:
            df = rule.inverse_transform(df)
        return df

    def fit_transform(self, df: pd.DataFrame) -> pd.DataFrame:
        return self.fit(df).transform(df)

    def set_default_values(self, default_value_rules: Mapping[str, Union[int, str, None]]) -> None:
        by_column = {rule.column: rule for rule in self.rules}
        for column, value in default_value_rules.items():
            if column not in by_column:
                msg = f"No encoding rule for column '{column}'."
                raise ValueError(msg)
            by_column[column].set_default_value(value)

    def set_handle_unknowns(self, handle_unknown_rules: Mapping[str, HandleUnknownStrategies]) -> None:
        by_column = {rule.column: rule for rule in self.rules}
        for column, value in handle_unknown_rules.items():
            if column not in by_column:
                msg = f"No encoding rule for column '{column}'."
                raise ValueError(msg)
            by_column[column].set_handle_unknown(value)

    # -- persistence -------------------------------------------------------
    def save(self, path: str) -> None:
        """Write rules + fitted mappings to ``<path>.replay`` (ref tokenizer
        save, replay/data/nn/sequence_tokenizer.py:463-509)."""
        import json
        from pathlib import Path

        target = Path(path).with_suffix(".replay")
        target.mkdir(parents=True, exist_ok=True)
        payload = {"_class_name": "LabelEncoder", "rules": [r._as_dict() for r in self.rules]}
        (target / "init_args.json").write_text(json.dumps(payload))

    @classmethod
    def load(cls, path: str) -> "LabelEncoder":
        import json
        from pathlib import Path

        source = Path(path).with_suffix(".replay")
        payload = json.loads((source / "init_args.json").read_text())
        return cls([LabelEncodingRule._from_dict(spec) for spec in payload["rules"]])


_RULE_CLASSES = {
    "LabelEncodingRule": LabelEncodingRule,
    "SequenceEncodingRule": SequenceEncodingRule,
}
