"""Pad ragged list columns to a fixed width.

Capability parity with the reference ``replay/experimental/preprocessing/padder.py:11``
(``Padder``), pandas-native. Static shapes are the TPU contract — this is the
host-side tool that turns ragged per-row lists into fixed-width lists before
they are stacked into ``[B, L]`` arrays (see ``data/nn/iterator.py`` for the
batching equivalent that also emits validity masks).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np
import pandas as pd
from pandas.api.types import is_object_dtype

PadValue = Union[str, float, int, None]


class Padder:
    """Cut and pad list-valued dataframe columns to ``array_size``.

    >>> df = pd.DataFrame({"items": [[1], [1, 2, 3]]})
    >>> Padder(pad_columns="items", array_size=2).transform(df)["items"].tolist()
    [[1, 0], [2, 3]]
    """

    def __init__(
        self,
        pad_columns: Union[str, List[str]],
        padding_side: str = "right",
        padding_value: Union[PadValue, List[PadValue]] = 0,
        array_size: Optional[int] = None,
        cut_array: bool = True,
        cut_side: str = "right",
    ) -> None:
        """
        :param pad_columns: list-valued column name(s) to process.
        :param padding_side: where fill values go, ``"right"`` or ``"left"``.
        :param padding_value: fill value, one per column (a scalar is
            broadcast to every column).
        :param array_size: target width; ``None`` uses each column's max
            list length.
        :param cut_array: whether to truncate lists longer than the target.
        :param cut_side: ``"right"`` keeps the tail (most recent items),
            ``"left"`` keeps the head.
        """
        self.pad_columns = [pad_columns] if isinstance(pad_columns, str) else list(pad_columns)
        if padding_side not in ("right", "left"):
            msg = f"padding_side must be 'right' or 'left', got {padding_side}"
            raise ValueError(msg)
        if cut_side not in ("right", "left"):
            msg = f"cut_side must be 'right' or 'left', got {cut_side}"
            raise ValueError(msg)
        values: List[PadValue]
        if isinstance(padding_value, (str, bytes)) or not isinstance(padding_value, Sequence):
            values = [padding_value]
        else:
            values = list(padding_value)
        if len(values) == 1 and len(self.pad_columns) > 1:
            values = values * len(self.pad_columns)
        if len(values) != len(self.pad_columns):
            msg = "pad_columns and padding_value must have the same length"
            raise ValueError(msg)
        self.padding_value = values
        if array_size is not None and (not isinstance(array_size, int) or array_size < 1):
            msg = f"array_size must be a positive integer, got {array_size}"
            raise ValueError(msg)
        self.array_size = array_size
        self.padding_side = padding_side
        self.cut_array = cut_array
        self.cut_side = cut_side

    @staticmethod
    def _as_list(sample) -> list:
        """Cell -> python list; tuples/ndarrays (e.g. parquet round-trips)
        count as sequences, None/NaN as empty. A non-null SCALAR cell is an
        error: silently mapping it to [] would turn a column of scalars into
        pure padding rows with no signal that the input was malformed."""
        if isinstance(sample, list):
            return sample
        if isinstance(sample, (tuple, np.ndarray)):
            return list(sample)
        if sample is None or (not isinstance(sample, (str, bytes)) and pd.isna(sample)):
            return []
        msg = (
            "Padder pad-column cells must be lists/tuples/ndarrays or null, "
            f"got {type(sample).__name__}: {sample!r}"
        )
        raise ValueError(msg)

    def _pad_one(self, sample, width: int, fill) -> list:
        sample = self._as_list(sample)
        if self.cut_array and len(sample) > width:
            sample = sample[-width:] if self.cut_side == "right" else sample[:width]
        missing = width - len(sample)
        if missing <= 0:
            return sample
        pad = [fill] * missing
        return sample + pad if self.padding_side == "right" else pad + sample

    def transform(self, interactions: pd.DataFrame) -> pd.DataFrame:
        """Return a copy of ``interactions`` with the pad columns widened."""
        out = interactions.copy()
        for col, fill in zip(self.pad_columns, self.padding_value):
            if col not in out.columns:
                msg = f"Column {col} not in DataFrame columns."
                raise ValueError(msg)
            if not is_object_dtype(out[col]):
                msg = f"Column {col} should hold python lists (object dtype)."
                raise ValueError(msg)
            width = self.array_size
            if width is None:
                lengths = out[col].map(lambda x: len(self._as_list(x)))
                width = int(lengths.max()) if len(lengths) else 0
            out[col] = [self._pad_one(sample, width, fill) for sample in out[col]]
        return out
