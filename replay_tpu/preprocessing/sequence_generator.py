"""Expand an interaction log into next-item prediction examples.

Capability parity with the reference
``replay/experimental/preprocessing/sequence_generator.py:13`` (``SequenceGenerator``),
pandas-native. Every interaction becomes one training example whose input is
the (up to ``len_window``) preceding interactions of the same group and whose
label is the interaction itself; group-initial rows (empty history) are
dropped.
"""

from __future__ import annotations

from typing import List, Optional, Union

import pandas as pd


class SequenceGenerator:
    """Build ``(history list | next item)`` examples per group.

    >>> log = pd.DataFrame({
    ...     "user_id": [1, 1, 1],
    ...     "item_id": [3, 7, 10],
    ...     "timestamp": [1, 2, 3],
    ... })
    >>> SequenceGenerator("user_id", orderby_column="timestamp",
    ...                   transform_columns="item_id").transform(log)[
    ...     ["user_id", "item_id_list", "label_item_id"]].values.tolist()
    [[1, [3], 7], [1, [3, 7], 10]]
    """

    def __init__(
        self,
        groupby_column: Union[str, List[str]],
        orderby_column: Optional[Union[str, List[str]]] = None,
        transform_columns: Optional[Union[str, List[str]]] = None,
        len_window: int = 50,
        sequence_prefix: Optional[str] = None,
        sequence_suffix: Optional[str] = "_list",
        label_prefix: Optional[str] = "label_",
        label_suffix: Optional[str] = None,
        get_list_len: bool = False,
        list_len_column: str = "list_len",
    ) -> None:
        """
        :param groupby_column: grouping key(s) — usually the user column.
        :param orderby_column: sort key(s) defining sequence order; ``None``
            keeps the frame's order within each group.
        :param transform_columns: columns to expand into history lists;
            ``None`` processes every non-grouping column.
        :param len_window: maximum history length kept per example.
        :param sequence_prefix: prefix for generated history columns.
        :param sequence_suffix: suffix for generated history columns.
        :param label_prefix: prefix for generated label columns.
        :param label_suffix: suffix for generated label columns.
        :param get_list_len: also emit the history length per example.
        :param list_len_column: name of the length column.
        """
        if len_window < 1:
            msg = f"len_window must be positive, got {len_window}"
            raise ValueError(msg)
        self.groupby_column = [groupby_column] if isinstance(groupby_column, str) else list(groupby_column)
        if orderby_column is None:
            self.orderby_column = None
        else:
            self.orderby_column = [orderby_column] if isinstance(orderby_column, str) else list(orderby_column)
        self.transform_columns = (
            [transform_columns] if isinstance(transform_columns, str) else transform_columns
        )
        self.len_window = len_window
        self.sequence_prefix = sequence_prefix or ""
        self.sequence_suffix = sequence_suffix or ""
        self.label_prefix = label_prefix or ""
        self.label_suffix = label_suffix or ""
        self.get_list_len = get_list_len
        self.list_len_column = list_len_column

    def _seq_name(self, col: str) -> str:
        return f"{self.sequence_prefix}{col}{self.sequence_suffix}"

    def _label_name(self, col: str) -> str:
        return f"{self.label_prefix}{col}{self.label_suffix}"

    def transform(self, interactions: pd.DataFrame) -> pd.DataFrame:
        """Return the example frame (group keys, history lists, labels)."""
        transform_columns = self.transform_columns
        if transform_columns is None:
            transform_columns = [c for c in interactions.columns if c not in self.groupby_column]

        ordered = interactions.sort_values(
            by=self.orderby_column if self.orderby_column is not None else self.groupby_column,
            kind="stable",
        )

        rows: dict = {col: [] for col in self.groupby_column}
        for col in transform_columns:
            rows[self._seq_name(col)] = []
            rows[self._label_name(col)] = []
        if self.get_list_len:
            rows[self.list_len_column] = []

        for keys, group in ordered.groupby(self.groupby_column, sort=False):
            if not isinstance(keys, tuple):
                keys = (keys,)
            histories = {col: group[col].tolist() for col in transform_columns}
            n = len(group)
            for i in range(1, n):  # row 0 has no history and is dropped
                lo = max(0, i - self.len_window)
                for key_col, key in zip(self.groupby_column, keys):
                    rows[key_col].append(key)
                for col in transform_columns:
                    values = histories[col]
                    rows[self._seq_name(col)].append(values[lo:i])
                    rows[self._label_name(col)].append(values[i])
                if self.get_list_len:
                    rows[self.list_len_column].append(i - lo)
        return pd.DataFrame(rows)
