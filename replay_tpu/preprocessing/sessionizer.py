"""Session assignment from timestamp gaps.

Capability parity with replay/preprocessing/sessionizer.py:11: a new session
starts whenever the gap to the previous event of the same query exceeds
``session_gap``; sessions shorter than ``min_session_length`` or longer than
``max_session_length`` can be dropped. Vectorized pandas (sort + diff + cumsum),
no per-user loops.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import pandas as pd


class Sessionizer:
    """Assign session ids from per-query timestamp gaps (ref: replay/preprocessing/sessionizer.py).

    >>> import pandas as pd
    >>> log = pd.DataFrame({"query_id": [1, 1, 1], "timestamp": [0.0, 10.0, 500.0]})
    >>> Sessionizer(session_gap=100.0).transform(log)["session_id"].nunique()
    2
    """

    def __init__(
        self,
        session_gap: float = 86400.0,
        query_column: str = "query_id",
        timestamp_column: str = "timestamp",
        session_column: str = "session_id",
        min_session_length: Optional[int] = None,
        max_session_length: Optional[int] = None,
    ) -> None:
        if session_gap <= 0:
            msg = "session_gap must be positive"
            raise ValueError(msg)
        self.session_gap = session_gap
        self.query_column = query_column
        self.timestamp_column = timestamp_column
        self.session_column = session_column
        self.min_session_length = min_session_length
        self.max_session_length = max_session_length

    def transform(self, interactions: pd.DataFrame) -> pd.DataFrame:
        ordered = interactions.assign(__pos=np.arange(len(interactions))).sort_values(
            [self.query_column, self.timestamp_column], kind="stable"
        )
        ts = ordered[self.timestamp_column]
        if np.issubdtype(ts.dtype, np.datetime64):
            gaps = ts.diff().dt.total_seconds()
        else:
            gaps = ts.diff()
        new_query = ordered[self.query_column] != ordered[self.query_column].shift()
        boundary = new_query | (gaps > self.session_gap)
        ordered = ordered.assign(**{self.session_column: boundary.cumsum() - 1})
        if self.min_session_length is not None or self.max_session_length is not None:
            sizes = ordered.groupby(self.session_column)[self.session_column].transform("size")
            keep = pd.Series(True, index=ordered.index)
            if self.min_session_length is not None:
                keep &= sizes >= self.min_session_length
            if self.max_session_length is not None:
                keep &= sizes <= self.max_session_length
            ordered = ordered[keep]
        return ordered.sort_values("__pos", kind="stable").drop(columns="__pos")
