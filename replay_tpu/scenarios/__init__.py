from .fallback import Fallback
from .two_stages import LogisticReranker, TwoStages

__all__ = ["Fallback", "LogisticReranker", "TwoStages"]
