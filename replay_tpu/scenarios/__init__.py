from .fallback import Fallback

__all__ = ["Fallback"]
