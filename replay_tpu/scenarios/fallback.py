"""Fallback scenario: a main model backed by a cold-capable fallback.

Capability parity with replay/scenarios/fallback.py:13: both models fit on the
same dataset; at predict time every query gets the main model's recommendations,
topped up from the fallback (popularity by default) whenever the main model
returns fewer than ``k`` items — cold queries the main model cannot score at all
are served entirely by the fallback.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import pandas as pd

from replay_tpu.data.dataset import Dataset
from replay_tpu.models.base import BaseRecommender
from replay_tpu.models.pop_rec import PopRec


class Fallback(BaseRecommender):
    def __init__(self, main: BaseRecommender, fallback: Optional[BaseRecommender] = None) -> None:
        super().__init__()
        self.main = main
        self.fallback = fallback if fallback is not None else PopRec()

    def _fit(self, dataset: Dataset) -> None:
        self.main.fit(dataset)
        self.fallback.fit(dataset)

    def predict(
        self, dataset, k: int, queries=None, items=None, filter_seen_items: bool = True
    ) -> pd.DataFrame:
        self._check_fitted()
        main_recs = self.main.predict(dataset, k, queries, items, filter_seen_items)
        fallback_recs = self.fallback.predict(dataset, k, queries, items, filter_seen_items)
        if queries is None:
            queries = (
                np.sort(dataset.interactions[self.query_column].unique())
                if dataset is not None
                else self.fit_queries
            )
        # shift fallback scores strictly below the main model's minimum so the
        # top-k never prefers a fallback item over a main one
        if len(main_recs) and len(fallback_recs):
            offset = float(main_recs["rating"].min()) - float(fallback_recs["rating"].max()) - 1.0
            fallback_recs = fallback_recs.assign(rating=fallback_recs["rating"] + offset)
        combined = pd.concat([main_recs, fallback_recs], ignore_index=True)
        combined = combined.drop_duplicates(subset=[self.query_column, self.item_column], keep="first")
        combined = combined[combined[self.query_column].isin(np.asarray(queries))]
        return self._top_k(combined, k)

    def _predict_scores(self, dataset, queries, items) -> pd.DataFrame:  # pragma: no cover
        raise NotImplementedError("Fallback combines child predictions directly.")

    def save(self, path: str) -> None:
        import json
        from pathlib import Path

        self._check_fitted()
        target = Path(path).with_suffix(".replay")
        target.mkdir(parents=True, exist_ok=True)
        (target / "init_args.json").write_text(
            json.dumps(
                {
                    "_class_name": "Fallback",
                    "main": type(self.main).__name__,
                    "fallback": type(self.fallback).__name__,
                }
            )
        )
        (target / "fit_info.json").write_text(
            json.dumps(
                {
                    "query_column": self.query_column,
                    "item_column": self.item_column,
                    "fit_queries": self.fit_queries.tolist(),
                    "fit_items": self.fit_items.tolist(),
                }
            )
        )
        self.main.save(str(target / "main"))
        self.fallback.save(str(target / "fallback"))

    @classmethod
    def load(cls, path: str) -> "Fallback":
        import json
        from pathlib import Path

        import replay_tpu.models as model_registry

        source = Path(path).with_suffix(".replay")
        args = json.loads((source / "init_args.json").read_text())
        main_cls = getattr(model_registry, args["main"])
        fallback_cls = getattr(model_registry, args["fallback"])
        scenario = cls(
            main=main_cls.load(str(source / "main")),
            fallback=fallback_cls.load(str(source / "fallback")),
        )
        info = json.loads((source / "fit_info.json").read_text())
        scenario.query_column = info["query_column"]
        scenario.item_column = info["item_column"]
        scenario.fit_queries = np.asarray(info["fit_queries"])
        scenario.fit_items = np.asarray(info["fit_items"])
        return scenario
