"""Two-stage scenario: candidate generation → feature enrichment → reranking.

Capability parity with the reference experimental TwoStagesScenario
(replay/experimental/scenarios/two_stages/: first-level models generate
candidates, HistoryBasedFeaturesProcessor builds log features, a second-level
learner reranks; the reference plugs LightAutoML in as the reranker).

Reranker here: L2-regularized logistic regression trained with jitted
full-batch newton/gradient steps in JAX — honest, dependency-free, and easily
swapped (any object with fit(X, y)/predict_proba(X) works).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import pandas as pd

from replay_tpu.data.dataset import Dataset
from replay_tpu.models.base import BaseRecommender
from replay_tpu.preprocessing.history_based_fp import HistoryBasedFeaturesProcessor
from replay_tpu.splitters.strategies import RatioSplitter


class LogisticReranker:
    """Tiny L2 logistic regression (jitted adam), the default second stage."""

    def __init__(self, reg: float = 1e-3, steps: int = 300, learning_rate: float = 0.1) -> None:
        self.reg = reg
        self.steps = steps
        self.learning_rate = learning_rate
        self.weights: Optional[np.ndarray] = None

    def fit(self, features: np.ndarray, labels: np.ndarray) -> "LogisticReranker":
        import jax
        import jax.numpy as jnp
        import optax

        x = jnp.asarray(np.column_stack([features, np.ones(len(features))]), jnp.float32)
        y = jnp.asarray(labels, jnp.float32)
        w = jnp.zeros(x.shape[1], jnp.float32)
        tx = optax.adam(self.learning_rate)
        opt_state = tx.init(w)

        @jax.jit
        def step(w, opt_state):
            def loss_fn(w):
                logits = x @ w
                nll = jnp.mean(optax.sigmoid_binary_cross_entropy(logits, y))
                return nll + self.reg * jnp.sum(w**2)

            loss, grads = jax.value_and_grad(loss_fn)(w)
            updates, opt_state = tx.update(grads, opt_state)
            return optax.apply_updates(w, updates), opt_state

        for _ in range(self.steps):
            w, opt_state = step(w, opt_state)
        self.weights = np.asarray(w)
        return self

    @staticmethod
    def decision_function(features: np.ndarray, weights: np.ndarray) -> np.ndarray:
        """Pre-sigmoid margins ``features @ w[:-1] + w[-1]`` — the ONE formula
        shared by the host ``predict_proba`` and the on-device serve re-rank
        (``replay_tpu.serve.pipeline`` applies the same ``weights`` with
        ``jnp``), so serving scores stay faithful to the trained reranker."""
        features = np.asarray(features, np.float64)
        weights = np.asarray(weights, np.float64)
        if features.shape[-1] != weights.shape[0] - 1:
            msg = (
                f"feature dim {features.shape[-1]} does not match reranker "
                f"weights trained on {weights.shape[0] - 1} features"
            )
            raise ValueError(msg)
        return features @ weights[:-1] + weights[-1]

    @property
    def serving_weights(self) -> np.ndarray:
        """Trained ``[n_features + 1]`` weights (bias last) for the serve
        pipeline's on-device re-rank; raises before :meth:`fit`."""
        if self.weights is None:
            msg = "LogisticReranker has no trained weights yet (call fit first)"
            raise ValueError(msg)
        return np.asarray(self.weights)

    def predict_proba(self, features: np.ndarray) -> np.ndarray:
        return 1.0 / (1.0 + np.exp(-self.decision_function(features, self.serving_weights)))


class TwoStages(BaseRecommender):
    """Candidate generators + history features + a trained reranker."""

    def __init__(
        self,
        first_level_models: Sequence[BaseRecommender],
        reranker=None,
        num_candidates: int = 50,
        features_processor: Optional[HistoryBasedFeaturesProcessor] = None,
        holdout_fraction: float = 0.3,
        seed: int = 0,
    ) -> None:
        super().__init__()
        self.first_level_models = list(first_level_models)
        self.reranker = reranker if reranker is not None else LogisticReranker()
        self.num_candidates = num_candidates
        self.features_processor = features_processor or HistoryBasedFeaturesProcessor()
        self.holdout_fraction = holdout_fraction
        self.seed = seed
        self._model_names: List[str] = []
        self._feature_column_order: Optional[List[str]] = None

    def _candidate_frame(self, dataset: Dataset, k: int, queries=None) -> pd.DataFrame:
        """Union of every generator's top-k with per-model score columns."""
        frames = []
        for idx, model in enumerate(self.first_level_models):
            recs = model.predict(dataset, k, queries=queries, filter_seen_items=True)
            recs = recs.rename(columns={"rating": f"score_{idx}"})
            frames.append(recs)
        out = frames[0]
        for frame in frames[1:]:
            out = out.merge(frame, on=[self.query_column, self.item_column], how="outer")
        score_columns = [c for c in out.columns if c.startswith("score_")]
        return out.fillna({c: 0.0 for c in score_columns})

    def _feature_matrix(self, pairs: pd.DataFrame) -> np.ndarray:
        enriched = self.features_processor.transform(
            pairs[[self.query_column, self.item_column]]
        )
        score_columns = [c for c in pairs.columns if c.startswith("score_")]
        feature_columns = [
            c for c in enriched.columns if c not in (self.query_column, self.item_column)
        ]
        if self._feature_column_order is None:
            self._feature_column_order = feature_columns
        else:
            # serving features must align with the trained weights: refitting the
            # processor on the full log can add/drop pivot columns, so reindex to
            # the training-time column order (missing -> 0, extras dropped)
            enriched = enriched.reindex(columns=self._feature_column_order, fill_value=0.0)
            feature_columns = self._feature_column_order
        return np.column_stack(
            [pairs[score_columns].to_numpy(np.float64), enriched[feature_columns].to_numpy(np.float64)]
        )

    def _fit(self, dataset: Dataset) -> None:
        # split history: generators fit on the base part, the reranker learns to
        # predict the held-out positives among generated candidates
        base, holdout = RatioSplitter(
            test_size=self.holdout_fraction,
            divide_column=self.query_column,
            query_column=self.query_column,
            item_column=self.item_column,
        ).split(dataset.interactions)
        base_dataset = Dataset(
            feature_schema=dataset.feature_schema.copy(),
            interactions=base,
            query_features=dataset.query_features,
            item_features=dataset.item_features,
            check_consistency=False,
        )
        for model in self.first_level_models:
            model.fit(base_dataset)
        self.features_processor.fit(base, dataset.query_features, dataset.item_features)
        self._feature_column_order = None  # rebound to this fit's training features

        candidates = self._candidate_frame(base_dataset, self.num_candidates)
        positives = holdout[[self.query_column, self.item_column]].assign(__label=1.0)
        training = candidates.merge(
            positives, on=[self.query_column, self.item_column], how="left"
        )
        labels = training["__label"].fillna(0.0).to_numpy()
        features = self._feature_matrix(training)
        self.reranker.fit(features, labels)

        # refit generators + features on the FULL history for serving
        for model in self.first_level_models:
            model.fit(dataset)
        self.features_processor.fit(
            dataset.interactions, dataset.query_features, dataset.item_features
        )

    def predict(
        self, dataset, k: int, queries=None, items=None, filter_seen_items: bool = True
    ) -> pd.DataFrame:
        self._check_fitted()
        candidates = self._candidate_frame(dataset, self.num_candidates, queries=queries)
        if items is not None:
            candidates = candidates[candidates[self.item_column].isin(np.asarray(items))]
        features = self._feature_matrix(candidates)
        scored = candidates[[self.query_column, self.item_column]].assign(
            rating=self.reranker.predict_proba(features)
        )
        if filter_seen_items and dataset is not None:
            seen = dataset.interactions[[self.query_column, self.item_column]]
            scored = scored.merge(
                seen.assign(__seen=True), on=[self.query_column, self.item_column], how="left"
            )
            scored = scored[scored["__seen"].isna()].drop(columns="__seen")
        return self._top_k(scored, k)

    def _predict_scores(self, dataset, queries, items):  # pragma: no cover
        raise NotImplementedError("TwoStages reranks candidate frames directly.")
