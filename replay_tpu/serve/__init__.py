"""On-device scoring service: micro-batched, state-cached, candidate→rank fused.

The production-serving analog of the reference's OpenVINO-compiled-model +
ANN-index stack (SURVEY §2.8), built from this repo's own pieces:

* :class:`MicroBatcher` — fills fixed ``[B, L]`` slots from concurrent
  requests under a max-wait deadline (``batcher``).
* :class:`UserStateCache` — per-user encoded-state LRU with one-step
  incremental window advances (``cache``).
* :class:`ScoringEngine` — pre-compiled ``CompiledInference`` bucket
  executables per length bucket + cached-state scorers (``engine``).
* :class:`CandidatePipeline` — exact sharded MIPS retrieval fused with the
  two-stage re-rank and top-k, all on device (``pipeline``).
* :class:`ScoringService` — the end-to-end service (``service``).

``bench_serve.py`` (repo root) drives it with closed/open-loop load and emits
the QPS/latency/fill/hit-rate record ``obs.report`` renders and gates on.
See docs/serving.md.
"""

from .batcher import MicroBatcher
from .cache import UserState, UserStateCache
from .engine import ScoringEngine
from .pipeline import CandidatePipeline
from .request import ScoreRequest, ScoreResponse, make_window
from .service import ScoringService

__all__ = [
    "CandidatePipeline",
    "MicroBatcher",
    "ScoreRequest",
    "ScoreResponse",
    "ScoringEngine",
    "ScoringService",
    "UserState",
    "UserStateCache",
    "make_window",
]
