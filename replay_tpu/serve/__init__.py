"""On-device scoring service: micro-batched, state-cached, candidate→rank fused.

The production-serving analog of the reference's OpenVINO-compiled-model +
ANN-index stack (SURVEY §2.8), built from this repo's own pieces:

* :class:`MicroBatcher` — fills fixed ``[B, L]`` slots from concurrent
  requests under a max-wait deadline, with bounded per-lane queues and a
  supervised worker (``batcher``).
* :class:`UserStateCache` — per-user encoded-state LRU with one-step
  incremental window advances (``cache``).
* :class:`ScoringEngine` — pre-compiled ``CompiledInference`` bucket
  executables per length bucket + cached-state scorers (``engine``).
* :class:`CandidatePipeline` — exact sharded MIPS retrieval fused with the
  two-stage re-rank and top-k, all on device (``pipeline``).
* :class:`CircuitBreaker` — closed→open→half-open supervision of the encode
  path (``breaker``), and :class:`FallbackScorer` — the host-side popularity
  floor of the degradation ladder (``degrade``).
* :class:`ScoringService` — the end-to-end service (``service``), with
  admission control (:class:`RequestShed`), per-request deadlines
  (:class:`DeadlineExceeded`) and graceful degradation (``served_by`` tags).
* :class:`ParamStore` / :class:`PromotionController` — zero-downtime weight
  swaps and SLO-guarded canary promotion (``promote``): versioned parameter
  generations hot-swap into the running executables without recompiling,
  behind a shadow→canary→promoted|rolled_back state machine (docs/robustness
  "Zero-downtime swaps and canary promotion").
* :class:`ServingFleet` / :class:`HashRing` — N replicas behind a host-side
  consistent-hash router (``fleet``/``router``): bounded-movement user →
  replica mapping so state caches stay hot, per-replica health states
  (healthy → degraded → draining → dead) driven by heartbeats + exporter
  gauges, failover with the rerouted users riding the degradation ladder,
  p99-hedged requests, retry backoff honoring ``retry_after_s``, and a
  drain-and-swap rollout composing with the promotion path (docs/serving.md
  "The fleet").

``bench_serve.py`` (repo root) drives it with closed/open-loop load — plus
open-loop OVERLOAD and ``--chaos`` fault-injection modes — and emits the
QPS/latency/fill/hit-rate/shed-rate record ``obs.report`` renders and gates
on. See docs/serving.md.

Attach an :class:`~replay_tpu.obs.QualityMonitor` via ``ScoringService(
quality=...)`` to watch the MODEL-quality plane of the same traffic (online
prequential hitrate/NDCG, coverage/novelty/surprisal, PSI drift — docs/
observability.md "The quality plane"); :func:`top_k_cut` is the shared
ranked-cut contract over both :class:`ScoreResponse` shapes it relies on.
"""

from .batcher import MicroBatcher
from .breaker import CircuitBreaker
from .cache import UserState, UserStateCache
from .degrade import DEGRADATION_LADDER, FallbackScorer
from .engine import ScoringEngine
from .errors import (
    CircuitOpen,
    DeadlineExceeded,
    NoHealthyReplica,
    RequestShed,
    ServeError,
    ServiceClosed,
)
from .fleet import ReplicaHandle, ServingFleet
from .pipeline import CandidatePipeline
from .promote import (
    PROMOTION_STAGES,
    ParamGeneration,
    ParamStore,
    PromotionController,
    in_canary_slice,
)
from .quant import QuantizedTable, quantization_error, quantize_embeddings
from .remote import RemoteReplica, ReplicaServer, ReplicaServerProcess
from .request import ScoreRequest, ScoreResponse, make_window, top_k_cut
from .router import REPLICA_HEALTH, BackoffPolicy, HashRing, ReplicaHealth
from .service import ScoringService

__all__ = [
    "DEGRADATION_LADDER",
    "PROMOTION_STAGES",
    "REPLICA_HEALTH",
    "BackoffPolicy",
    "CandidatePipeline",
    "CircuitBreaker",
    "CircuitOpen",
    "DeadlineExceeded",
    "FallbackScorer",
    "HashRing",
    "MicroBatcher",
    "NoHealthyReplica",
    "ParamGeneration",
    "ParamStore",
    "PromotionController",
    "RemoteReplica",
    "ReplicaHandle",
    "ReplicaHealth",
    "ReplicaServer",
    "ReplicaServerProcess",
    "RequestShed",
    "ScoreRequest",
    "ScoreResponse",
    "ScoringEngine",
    "ScoringService",
    "ServeError",
    "ServiceClosed",
    "ServingFleet",
    "UserState",
    "QuantizedTable",
    "UserStateCache",
    "in_canary_slice",
    "make_window",
    "quantization_error",
    "quantize_embeddings",
    "top_k_cut",
]
