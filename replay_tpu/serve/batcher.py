"""Request micro-batcher: fixed-slot batches under a max-wait deadline.

The serving counterpart of ``SequenceBatcher``'s static-shape rule: XLA wants
a handful of compiled shapes, live traffic arrives one request at a time, so
concurrent requests are collected into per-lane queues (a lane = one compiled
program family, e.g. ``encode:L=16`` or ``hit``) and dispatched as a batch
when either the lane FILLS its largest compiled bucket or the OLDEST request's
deadline (``max_wait`` after enqueue) expires — whichever comes first. Partial
batches are padded up to the nearest bucket by the engine and masked by row
validity, so a lone request costs one bucket-1 program, not a 512-wide slot.

Single worker thread: every dispatch (and therefore every device call) runs on
it sequentially — the accelerator is a serial resource anyway, and it keeps
the jax side single-threaded.

Resilience contract (docs/serving.md "Overload and degradation"):

* **admission control** — ``max_depth`` bounds each lane's queue; a submit
  into a full lane raises :class:`~replay_tpu.serve.errors.RequestShed`
  (depth + retry-after hint) instead of growing the backlog without bound.
* **supervision** — a worker crash (``on_error`` raising, or a non-``Exception``
  ``BaseException`` escaping a dispatch) fails the in-flight batch through
  ``on_error`` and restarts the loop, up to ``max_worker_restarts`` times;
  past the budget every queued item is failed with
  :class:`~replay_tpu.serve.errors.ServiceClosed` and the batcher refuses new
  work. Plain dispatch ``Exception``s still route to ``on_error`` without
  costing a restart.
* **no orphaned waiters** — ``stop()`` flushes what it can through
  ``dispatch`` and FAILS whatever remains (worker dead, or wedged past the
  join timeout — including the in-flight batch), so no submitted item is ever
  left unresolved.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Hashable, List, Optional, Tuple

from .errors import RequestShed, ServiceClosed


class MicroBatcher:
    """Collects submitted items into per-lane batches; a worker thread calls
    ``dispatch(lane, items)`` when a lane fills or its oldest item times out.

    :param dispatch: callback run ON THE WORKER THREAD with at most
        ``capacity(lane)`` items. ``Exception``s are routed to ``on_error``
        (the worker survives); anything ``on_error`` raises crashes the worker
        into the supervisor.
    :param capacity: max items per dispatched batch, per lane — the largest
        compiled batch bucket. Either a mapping or a default int for lanes not
        listed.
    :param max_wait: seconds a request may wait for co-riders before a partial
        batch is dispatched anyway (the latency/fill trade-off knob).
    :param on_error: ``(lane, items, exception) -> None``; resolves the failed
        items' futures at the service layer.
    :param max_depth: per-lane queued-item bound; ``None`` = unbounded (the
        pre-resilience behavior). Submits beyond it raise :class:`RequestShed`.
    :param max_worker_restarts: worker crashes tolerated before the batcher
        gives up and fails all pending work.
    """

    def __init__(
        self,
        dispatch: Callable[[Hashable, List[Any]], None],
        capacity: Any = 64,
        max_wait: float = 0.002,
        on_error: Optional[Callable[[Hashable, List[Any], BaseException], None]] = None,
        max_depth: Optional[int] = None,
        max_worker_restarts: int = 2,
    ) -> None:
        self._dispatch = dispatch
        self._capacity = capacity if isinstance(capacity, dict) else {}
        self._default_capacity = (
            max(self._capacity.values()) if isinstance(capacity, dict) and self._capacity
            else int(capacity) if not isinstance(capacity, dict) else 64
        )
        self.max_wait = float(max_wait)
        self.max_depth = int(max_depth) if max_depth is not None else None
        self.max_worker_restarts = int(max_worker_restarts)
        self._on_error = on_error
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._lanes: Dict[Hashable, deque] = {}
        self._running = False
        self._worker: Optional[threading.Thread] = None
        self._inflight: Optional[Tuple[Hashable, List[Any]]] = None
        self._dispatch_ewma = 0.0  # recent seconds per dispatched batch
        # accounting (under _lock)
        self.submitted = 0
        self.dispatched_batches = 0
        self.dispatched_rows = 0
        self.deadline_flushes = 0
        self.full_flushes = 0
        self.shed = 0
        self.worker_crashes = 0

    def capacity(self, lane: Hashable) -> int:
        return int(self._capacity.get(lane, self._default_capacity))

    # -- client side -------------------------------------------------------- #
    def submit(self, lane: Hashable, item: Any) -> None:
        """Enqueue ``item`` on ``lane`` (any thread).

        Raises :class:`ServiceClosed` once stopped (or crashed past the
        restart budget) and :class:`RequestShed` when the lane is at
        ``max_depth`` — both BEFORE the item is queued, so admission refusals
        never leave dangling state.
        """
        deadline = time.perf_counter() + self.max_wait
        with self._wake:
            if not self._running:
                raise ServiceClosed("MicroBatcher is not running")
            queue = self._lanes.setdefault(lane, deque())
            if self.max_depth is not None and len(queue) >= self.max_depth:
                self.shed += 1
                raise RequestShed(
                    lane,
                    depth=len(queue),
                    max_depth=self.max_depth,
                    retry_after_s=self._retry_after_locked(lane, len(queue)),
                )
            queue.append((deadline, item))
            self.submitted += 1
            self._wake.notify()

    @property
    def live(self) -> bool:
        """Whether the batcher currently accepts work (the fleet heartbeat's
        liveness bit): running and not crashed past the restart budget."""
        with self._lock:
            return self._running

    @property
    def idle(self) -> bool:
        """True when nothing is queued on any lane AND no batch is in flight
        — the drain protocol's 'safe to swap weights' condition."""
        with self._lock:
            return self._inflight is None and not any(self._lanes.values())

    def queued_depth(self, lane: Optional[Hashable] = None) -> int:
        """Items currently queued on ``lane`` (or across all lanes)."""
        with self._lock:
            if lane is not None:
                queue = self._lanes.get(lane)
                return len(queue) if queue else 0
            return sum(len(queue) for queue in self._lanes.values())

    def _retry_after_locked(self, lane: Hashable, depth: int) -> float:
        """Rough backlog-drain estimate: batches ahead x recent per-batch
        dispatch time, plus one max-wait for the fill window."""
        batches_ahead = max(depth, 1) / max(self.capacity(lane), 1)
        return batches_ahead * self._dispatch_ewma + self.max_wait

    # -- worker ------------------------------------------------------------- #
    def _pick(self, now: float) -> Optional[Tuple[Hashable, List[Any], bool]]:
        """Under the lock: (lane, items, was_full) ready to dispatch, or None.

        Expired deadlines win over full lanes: under sustained load one lane
        can be full on every worker iteration, and preferring it would let
        another lane's requests age unboundedly past ``max_wait`` — the
        contract is fill OR deadline, *whichever comes first*, per lane.
        """
        oldest_lane = None
        oldest_deadline = None
        full_lane = None
        for lane, queue in self._lanes.items():
            if not queue:
                continue
            if full_lane is None and len(queue) >= self.capacity(lane):
                full_lane = lane
            if oldest_deadline is None or queue[0][0] < oldest_deadline:
                oldest_deadline = queue[0][0]
                oldest_lane = lane
        if oldest_lane is not None and oldest_deadline <= now:
            was_full = len(self._lanes[oldest_lane]) >= self.capacity(oldest_lane)
            return oldest_lane, self._drain(oldest_lane), was_full
        if full_lane is not None:
            return full_lane, self._drain(full_lane), True
        return None

    def _drain(self, lane: Hashable) -> List[Any]:
        queue = self._lanes[lane]
        items = [queue.popleft()[1] for _ in range(min(len(queue), self.capacity(lane)))]
        return items

    def _next_deadline(self) -> Optional[float]:
        deadlines = [q[0][0] for q in self._lanes.values() if q]
        return min(deadlines) if deadlines else None

    def _run(self) -> None:
        """Worker main: the dispatch loop under a crash supervisor."""
        while True:
            try:
                self._loop()
                return  # clean exit: stopped and drained
            except BaseException as exc:  # noqa: BLE001 — supervised crash
                if not self._crashed(exc):
                    return
                # budget remains: loop around = the restart

    def _crashed(self, exc: BaseException) -> bool:
        """Fail the in-flight batch, decide restart vs give-up. Returns
        whether the loop should restart."""
        with self._wake:
            inflight, self._inflight = self._inflight, None
            self.worker_crashes += 1
            restart = self._running and self.worker_crashes <= self.max_worker_restarts
            if not restart:
                self._running = False  # refuse new work; pending fails below
        if inflight is not None:
            self._safe_on_error(inflight[0], inflight[1], exc)
        if not restart:
            self._fail_pending(
                ServiceClosed(
                    f"serve worker crashed ({exc!r}) and exhausted its "
                    f"{self.max_worker_restarts}-restart budget"
                )
            )
        return restart

    def _loop(self) -> None:
        while True:
            with self._wake:
                ready = self._pick(time.perf_counter())
                if ready is None:
                    if not self._running and not any(self._lanes.values()):
                        return
                    deadline = self._next_deadline()
                    if self._running:
                        timeout = (
                            None if deadline is None
                            else max(deadline - time.perf_counter(), 0.0)
                        )
                        self._wake.wait(timeout=timeout)
                        continue
                    # draining after stop(): flush whatever remains, no waiting
                    for lane, queue in self._lanes.items():
                        if queue:
                            ready = lane, self._drain(lane), False
                            break
                    if ready is None:
                        return
                lane, items, was_full = ready
                self.dispatched_batches += 1
                self.dispatched_rows += len(items)
                if was_full:
                    self.full_flushes += 1
                else:
                    self.deadline_flushes += 1
                self._inflight = (lane, items)
            started = time.perf_counter()
            try:
                self._dispatch(lane, items)
            except Exception as exc:  # noqa: BLE001 — worker survives
                # on_error raising (or a BaseException from dispatch) escapes
                # to the supervisor with _inflight still set, so the crashed
                # batch's items are failed rather than lost
                if self._on_error is not None:
                    self._on_error(lane, items, exc)
            elapsed = time.perf_counter() - started
            with self._wake:
                self._inflight = None
                self._dispatch_ewma = (
                    elapsed if not self._dispatch_ewma
                    else 0.8 * self._dispatch_ewma + 0.2 * elapsed
                )

    # -- failure resolution -------------------------------------------------- #
    def _safe_on_error(self, lane, items: List[Any], exc: BaseException) -> None:
        if self._on_error is None:
            return
        try:
            self._on_error(lane, items, exc)
        except Exception:  # noqa: BLE001 — resolution is best-effort by here
            pass

    def _fail_pending(self, exc: BaseException) -> None:
        """Drain every lane, failing each batch through ``on_error`` — the
        no-orphaned-waiters backstop for crash/stop paths."""
        while True:
            with self._wake:
                batch = None
                for lane, queue in self._lanes.items():
                    if queue:
                        batch = lane, [queue.popleft()[1] for _ in range(len(queue))]
                        break
                if batch is None:
                    return
            self._safe_on_error(batch[0], batch[1], exc)

    # -- lifecycle ---------------------------------------------------------- #
    def start(self) -> "MicroBatcher":
        with self._wake:
            if self._running:
                return self
            self._running = True
            self.worker_crashes = 0
            worker = self._worker
            if worker is not None and worker.is_alive():
                # a previous stop() timed out on a wedged dispatch: that
                # thread still owns the dispatch loop and resumes it when the
                # dispatch returns — spawning a second worker here would put
                # two threads on the device path (the single-worker invariant)
                self._wake.notify_all()
                return self
        self._worker = threading.Thread(target=self._run, name="serve-microbatcher", daemon=True)
        self._worker.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        """Stop accepting work, flush queued items through ``dispatch``, join.

        If the worker is dead or wedged past ``timeout``, every remaining item
        — queued AND in flight — is failed through ``on_error`` instead: a
        submitted item never outlives ``stop()`` unresolved.
        """
        with self._wake:
            if not self._running and self._worker is None:
                return
            self._running = False
            self._wake.notify_all()
        worker = self._worker
        if worker is not None:
            worker.join(timeout=timeout)
            if not worker.is_alive():
                self._worker = None
            # a wedged worker keeps its handle: a later start() must resume
            # it, never run a second dispatcher beside it
        # a healthy worker drained everything before exiting; leftovers mean
        # it crashed out or is wedged in a dispatch — fail them, don't hang
        self._fail_pending(
            ServiceClosed("MicroBatcher stopped before this request was served")
        )
        if worker is not None and worker.is_alive():
            with self._wake:
                inflight, self._inflight = self._inflight, None
            if inflight is not None:
                self._safe_on_error(
                    inflight[0],
                    inflight[1],
                    ServiceClosed(
                        "MicroBatcher stopped while this batch was in flight "
                        "(worker wedged past the join timeout)"
                    ),
                )

    def __enter__(self) -> "MicroBatcher":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.stop()

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "submitted": self.submitted,
                "dispatched_batches": self.dispatched_batches,
                "dispatched_rows": self.dispatched_rows,
                "deadline_flushes": self.deadline_flushes,
                "full_flushes": self.full_flushes,
                "shed": self.shed,
                "worker_crashes": self.worker_crashes,
                "queued": sum(len(queue) for queue in self._lanes.values()),
                "max_depth": self.max_depth,
            }
