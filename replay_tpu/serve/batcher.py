"""Request micro-batcher: fixed-slot batches under a max-wait deadline.

The serving counterpart of ``SequenceBatcher``'s static-shape rule: XLA wants
a handful of compiled shapes, live traffic arrives one request at a time, so
concurrent requests are collected into per-lane queues (a lane = one compiled
program family, e.g. ``encode:L=16`` or ``hit``) and dispatched as a batch
when either the lane FILLS its largest compiled bucket or the OLDEST request's
deadline (``max_wait`` after enqueue) expires — whichever comes first. Partial
batches are padded up to the nearest bucket by the engine and masked by row
validity, so a lone request costs one bucket-1 program, not a 512-wide slot.

Single worker thread: every dispatch (and therefore every device call) runs on
it sequentially — the accelerator is a serial resource anyway, and it keeps
the jax side single-threaded.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Hashable, List, Optional, Tuple


class MicroBatcher:
    """Collects submitted items into per-lane batches; a worker thread calls
    ``dispatch(lane, items)`` when a lane fills or its oldest item times out.

    :param dispatch: callback run ON THE WORKER THREAD with at most
        ``capacity(lane)`` items. Exceptions are routed to ``on_error`` (the
        worker survives).
    :param capacity: max items per dispatched batch, per lane — the largest
        compiled batch bucket. Either a mapping or a default int for lanes not
        listed.
    :param max_wait: seconds a request may wait for co-riders before a partial
        batch is dispatched anyway (the latency/fill trade-off knob).
    :param on_error: ``(lane, items, exception) -> None``; default re-raises
        into stderr logging via the worker guard in ``dispatch`` wrappers.
    """

    def __init__(
        self,
        dispatch: Callable[[Hashable, List[Any]], None],
        capacity: Any = 64,
        max_wait: float = 0.002,
        on_error: Optional[Callable[[Hashable, List[Any], BaseException], None]] = None,
    ) -> None:
        self._dispatch = dispatch
        self._capacity = capacity if isinstance(capacity, dict) else {}
        self._default_capacity = (
            max(self._capacity.values()) if isinstance(capacity, dict) and self._capacity
            else int(capacity) if not isinstance(capacity, dict) else 64
        )
        self.max_wait = float(max_wait)
        self._on_error = on_error
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._lanes: Dict[Hashable, deque] = {}
        self._running = False
        self._worker: Optional[threading.Thread] = None
        # accounting (under _lock)
        self.submitted = 0
        self.dispatched_batches = 0
        self.dispatched_rows = 0
        self.deadline_flushes = 0
        self.full_flushes = 0

    def capacity(self, lane: Hashable) -> int:
        return int(self._capacity.get(lane, self._default_capacity))

    # -- client side -------------------------------------------------------- #
    def submit(self, lane: Hashable, item: Any) -> None:
        """Enqueue ``item`` on ``lane`` (any thread). Raises once stopped."""
        deadline = time.perf_counter() + self.max_wait
        with self._wake:
            if not self._running:
                msg = "MicroBatcher is not running"
                raise RuntimeError(msg)
            self._lanes.setdefault(lane, deque()).append((deadline, item))
            self.submitted += 1
            self._wake.notify()

    # -- worker ------------------------------------------------------------- #
    def _pick(self, now: float) -> Optional[Tuple[Hashable, List[Any], bool]]:
        """Under the lock: (lane, items, was_full) ready to dispatch, or None.

        Expired deadlines win over full lanes: under sustained load one lane
        can be full on every worker iteration, and preferring it would let
        another lane's requests age unboundedly past ``max_wait`` — the
        contract is fill OR deadline, *whichever comes first*, per lane.
        """
        oldest_lane = None
        oldest_deadline = None
        full_lane = None
        for lane, queue in self._lanes.items():
            if not queue:
                continue
            if full_lane is None and len(queue) >= self.capacity(lane):
                full_lane = lane
            if oldest_deadline is None or queue[0][0] < oldest_deadline:
                oldest_deadline = queue[0][0]
                oldest_lane = lane
        if oldest_lane is not None and oldest_deadline <= now:
            was_full = len(self._lanes[oldest_lane]) >= self.capacity(oldest_lane)
            return oldest_lane, self._drain(oldest_lane), was_full
        if full_lane is not None:
            return full_lane, self._drain(full_lane), True
        return None

    def _drain(self, lane: Hashable) -> List[Any]:
        queue = self._lanes[lane]
        items = [queue.popleft()[1] for _ in range(min(len(queue), self.capacity(lane)))]
        return items

    def _next_deadline(self) -> Optional[float]:
        deadlines = [q[0][0] for q in self._lanes.values() if q]
        return min(deadlines) if deadlines else None

    def _run(self) -> None:
        while True:
            with self._wake:
                ready = self._pick(time.perf_counter())
                if ready is None:
                    if not self._running and not any(self._lanes.values()):
                        return
                    deadline = self._next_deadline()
                    if self._running:
                        timeout = (
                            None if deadline is None
                            else max(deadline - time.perf_counter(), 0.0)
                        )
                        self._wake.wait(timeout=timeout)
                        continue
                    # draining after stop(): flush whatever remains, no waiting
                    for lane, queue in self._lanes.items():
                        if queue:
                            ready = lane, self._drain(lane), False
                            break
                    if ready is None:
                        return
                lane, items, was_full = ready
                self.dispatched_batches += 1
                self.dispatched_rows += len(items)
                if was_full:
                    self.full_flushes += 1
                else:
                    self.deadline_flushes += 1
            try:
                self._dispatch(lane, items)
            except BaseException as exc:  # noqa: BLE001 — worker must survive
                if self._on_error is not None:
                    self._on_error(lane, items, exc)

    # -- lifecycle ---------------------------------------------------------- #
    def start(self) -> "MicroBatcher":
        with self._wake:
            if self._running:
                return self
            self._running = True
        self._worker = threading.Thread(target=self._run, name="serve-microbatcher", daemon=True)
        self._worker.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        """Stop accepting work, flush queued items through ``dispatch``, join."""
        with self._wake:
            if not self._running and self._worker is None:
                return
            self._running = False
            self._wake.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=timeout)
            self._worker = None

    def __enter__(self) -> "MicroBatcher":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.stop()

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "submitted": self.submitted,
                "dispatched_batches": self.dispatched_batches,
                "dispatched_rows": self.dispatched_rows,
                "deadline_flushes": self.deadline_flushes,
                "full_flushes": self.full_flushes,
            }
