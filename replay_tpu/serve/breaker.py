"""Circuit breaker over the scoring engine: closed → open → half-open → closed.

Host-only (no jax imports — unit-testable in the ``core`` tier with an
injected clock). The serving analog of training's ``RecoveryPolicy``: where
the trainer counts consecutive sentinel-skipped steps before a rollback, the
service counts consecutive :class:`~replay_tpu.serve.engine.ScoringEngine`
failures before it stops sending traffic at a broken device path.

State machine:

* **closed** — normal traffic. ``failure_threshold`` CONSECUTIVE recorded
  failures trip the breaker (one success resets the streak).
* **open** — encode traffic is refused at admission (the service degrades or
  sheds instead; see ``docs/serving.md``). After ``reset_timeout_s`` the next
  ``allow()`` transitions to half-open.
* **half-open** — up to ``half_open_max_probes`` requests are admitted as
  probes while the rest stay refused. One recorded success closes the breaker
  (full reset); one recorded failure reopens it and restarts the timer. A
  probe may also VANISH without an outcome (shed downstream, deadline-expired
  or cancelled before it reached the engine) — after ``reset_timeout_s`` with
  no outcome the probe slots are reclaimed and a fresh probe is admitted, so
  an abandoned probe can never wedge the breaker in half-open.

Thread-safe: ``allow()`` runs on client threads at admission,
``record_success``/``record_failure`` on the serve worker per engine call (a
micro-batch is ONE engine call, so a batch-wide exception counts once).
Transitions invoke ``on_transition(old, new, info)`` — the service forwards
these as ``on_breaker`` events.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

__all__ = ["CircuitBreaker"]

STATES = ("closed", "open", "half_open")


class CircuitBreaker:
    """Consecutive-failure breaker with a timed half-open probe window.

    :param failure_threshold: consecutive failures that open the breaker.
    :param reset_timeout_s: seconds the breaker stays open before the next
        ``allow()`` moves it to half-open.
    :param half_open_max_probes: probes admitted per half-open window before
        an outcome lands (more ``allow()`` calls are refused meanwhile).
    :param clock: monotonic-seconds source (injectable for tests).
    :param on_transition: ``(old_state, new_state, info: dict) -> None``,
        called OUTSIDE the breaker lock after every state change.
    """

    def __init__(
        self,
        failure_threshold: int = 5,
        reset_timeout_s: float = 2.0,
        half_open_max_probes: int = 1,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[[str, str, Dict[str, Any]], None]] = None,
    ) -> None:
        if failure_threshold < 1:
            msg = "failure_threshold must be >= 1"
            raise ValueError(msg)
        if half_open_max_probes < 1:
            msg = "half_open_max_probes must be >= 1"
            raise ValueError(msg)
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self.half_open_max_probes = int(half_open_max_probes)
        self.on_transition = on_transition
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive_failures = 0
        self._opened_at: Optional[float] = None
        self._probes_in_flight = 0
        self._probe_admitted_at: Optional[float] = None
        # accounting
        self.opens = 0
        self.closes = 0
        self.refusals = 0
        self.failures = 0
        self.successes = 0

    # -- queries ------------------------------------------------------------- #
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def retry_after_s(self) -> Optional[float]:
        """Remaining open window (None unless open)."""
        with self._lock:
            if self._state != "open" or self._opened_at is None:
                return None
            return max(self._opened_at + self.reset_timeout_s - self._clock(), 0.0)

    # -- the gate ------------------------------------------------------------ #
    def allow(self) -> bool:
        """May one more request enter the guarded path right now?

        Closed: always. Open: refuse until ``reset_timeout_s`` elapses, then
        transition to half-open and admit the first probe. Half-open: admit
        while fewer than ``half_open_max_probes`` probes are in flight.
        """
        transition = None
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if (
                    self._opened_at is not None
                    and self._clock() - self._opened_at >= self.reset_timeout_s
                ):
                    transition = self._transition_locked("half_open")
                    self._probes_in_flight = 1
                    self._probe_admitted_at = self._clock()
                else:
                    self.refusals += 1
                    allowed = False
            if self._state == "half_open" and transition is None:
                if self._probes_in_flight >= self.half_open_max_probes and (
                    self._probe_admitted_at is not None
                    and self._clock() - self._probe_admitted_at >= self.reset_timeout_s
                ):
                    # every admitted probe vanished without an outcome (shed,
                    # deadline-expired or cancelled before the engine): reclaim
                    # the slots — an abandoned probe must not wedge half-open
                    self._probes_in_flight = 0
                if self._probes_in_flight < self.half_open_max_probes:
                    self._probes_in_flight += 1
                    self._probe_admitted_at = self._clock()
                    allowed = True
                else:
                    self.refusals += 1
                    allowed = False
            elif transition is not None:
                allowed = True
        self._fire(transition)
        return allowed

    # -- outcomes ------------------------------------------------------------ #
    def record_success(self) -> None:
        """A guarded call succeeded: reset the streak; a half-open probe's
        success closes the breaker entirely."""
        transition = None
        with self._lock:
            self.successes += 1
            self._consecutive_failures = 0
            if self._state == "half_open":
                transition = self._transition_locked("closed")
        self._fire(transition)

    def record_failure(self) -> None:
        """A guarded call failed: extend the streak; at ``failure_threshold``
        the breaker opens, and any half-open probe failure reopens it."""
        transition = None
        with self._lock:
            self.failures += 1
            self._consecutive_failures += 1
            if self._state == "half_open" or (
                self._state == "closed"
                and self._consecutive_failures >= self.failure_threshold
            ):
                transition = self._transition_locked("open")
        self._fire(transition)

    # -- internals ----------------------------------------------------------- #
    def _transition_locked(self, new_state: str):
        old_state, self._state = self._state, new_state
        if new_state == "open":
            self.opens += 1
            self._opened_at = self._clock()
            self._probes_in_flight = 0
            self._probe_admitted_at = None
        elif new_state == "closed":
            self.closes += 1
            self._opened_at = None
            self._probes_in_flight = 0
            self._probe_admitted_at = None
            self._consecutive_failures = 0
        return (
            old_state,
            new_state,
            {
                "consecutive_failures": self._consecutive_failures,
                "opens": self.opens,
            },
        )

    def _fire(self, transition) -> None:
        if transition is not None and self.on_transition is not None:
            self.on_transition(*transition)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "opens": self.opens,
                "closes": self.closes,
                "refusals": self.refusals,
                "failures": self.failures,
                "successes": self.successes,
            }
