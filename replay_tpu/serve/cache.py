"""Per-user encoded-state cache with LRU eviction.

The serving-side answer to "returning users should not pay a full history
re-encode per request": the service caches, per user, the right-aligned item
window AND the encoder's last-position hidden state (the query embedding the
scoring head / MIPS retrieval consume). Request cost then depends on what
changed:

* nothing new → **pure hit**: the cached embedding is scored directly; the
  O(L·d²) transformer encode is skipped entirely.
* ``new_items`` → **advance**: the cached window rolls forward (one-step
  host-side state update; the client ships one event, not its history) and the
  canonical encode runs on the advanced window in a shared micro-batch —
  which is exactly why advanced scores stay BITWISE identical to a direct
  ``forward_inference`` of the updated history at the routed bucket (SASRec's
  positional table is tail-anchored, so appending shifts every position's
  embedding row; any "incremental" KV shortcut that skips re-attention would
  change the math, not just the bits).
* unknown user / explicit ``history`` → **cold**: full re-encode from the
  provided history (the exact-parity fallback), state inserted into the cache.

Thread-safe: client threads resolve states while the serve worker refreshes
embeddings after each encode.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, Hashable, Optional, Sequence

import numpy as np

from .request import make_window


@dataclass
class UserState:
    """One user's cached serving state (window right-aligned to ``[L_max]``)."""

    window: np.ndarray  # [L_max] int32
    mask: np.ndarray  # [L_max] bool
    length: int  # valid events in the window (<= L_max)
    embedding: Optional[np.ndarray] = None  # [E] last-position hidden state
    generation: int = 0  # bumped on every advance/refresh (stale-write guard)
    # the PARAM generation whose encoder produced ``embedding`` (serve.promote
    # hot swaps): an embedding encoded by generation G must only ever be
    # scored by generation G's scorer — the service treats a mismatch as an
    # embedding miss and re-encodes, never mixing generations in one response
    param_generation: int = 0


class UserStateCache:
    """LRU map ``user_id -> UserState`` with hit/advance/eviction accounting."""

    def __init__(self, capacity: int = 10_000) -> None:
        if capacity <= 0:
            msg = "cache capacity must be positive"
            raise ValueError(msg)
        self.capacity = int(capacity)
        self._states: "OrderedDict[Hashable, UserState]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.advances = 0
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._states)

    def lookup(self, user_id: Hashable) -> Optional[UserState]:
        """The user's state (refreshing LRU recency), or None; counts the
        hit/miss either way."""
        with self._lock:
            state = self._states.get(user_id)
            if state is None:
                self.misses += 1
                return None
            self._states.move_to_end(user_id)
            self.hits += 1
            return state

    def peek(self, user_id: Hashable) -> Optional[UserState]:
        """Like :meth:`lookup` but with no recency/counter side effects."""
        with self._lock:
            return self._states.get(user_id)

    def store(self, user_id: Hashable, state: UserState) -> None:
        with self._lock:
            self._states[user_id] = state
            self._states.move_to_end(user_id)
            while len(self._states) > self.capacity:
                self._states.popitem(last=False)
                self.evictions += 1

    @staticmethod
    def _advanced(state: UserState, new_items: Sequence[int], pad_id: int) -> UserState:
        max_len = state.window.shape[0]
        valid = state.window[state.mask] if state.length else np.zeros(0, np.int32)
        items = np.concatenate([valid, np.asarray(new_items, np.int32)])
        window, mask, length = make_window(items, max_len, pad_id)
        return UserState(
            window=window,
            mask=mask,
            length=length,
            embedding=None,
            generation=state.generation + 1,
        )

    def advance(self, state: UserState, new_items: Sequence[int], pad_id: int = 0) -> UserState:
        """The one-step incremental update: append ``new_items`` to the cached
        window (rolling the oldest events out once full). The embedding is
        dropped — it certifies the PREVIOUS window; the serve worker refreshes
        it from the next canonical encode. Pure (does not touch the map) —
        :meth:`advance_user` is the atomic lookup+advance+store most callers
        want."""
        self.advances += 1
        return self._advanced(state, new_items, pad_id)

    def advance_user(
        self, user_id: Hashable, new_items: Sequence[int], pad_id: int = 0
    ) -> Optional[UserState]:
        """Atomically advance ``user_id``'s cached window by ``new_items`` and
        return the new state (None when the user is not cached). One lock
        acquisition covers lookup→advance→store: two clients appending
        concurrently both land their items instead of the last write erasing
        the other's interaction."""
        with self._lock:
            state = self._states.get(user_id)
            if state is None:
                self.misses += 1
                return None
            self.hits += 1
            self.advances += 1
            advanced = self._advanced(state, new_items, pad_id)
            self._states[user_id] = advanced
            self._states.move_to_end(user_id)
            return advanced

    def refresh_embedding(
        self,
        user_id: Hashable,
        state: UserState,
        embedding: np.ndarray,
        param_generation: int = 0,
    ) -> None:
        """Attach the just-encoded hidden state — unless the user advanced
        again while the batch was in flight (generation moved on), in which
        case the stale embedding must not overwrite the newer window's slot.
        Check and store happen under ONE lock acquisition, so an advance
        landing between them cannot be clobbered. ``param_generation`` stamps
        WHICH parameter generation encoded the state (the hot-swap staleness
        guard)."""
        with self._lock:
            current = self._states.get(user_id)
            if current is not None and current.generation > state.generation:
                return
            state.embedding = np.asarray(embedding)
            state.param_generation = int(param_generation)
            self._states[user_id] = state
            self._states.move_to_end(user_id)
            while len(self._states) > self.capacity:
                self._states.popitem(last=False)
                self.evictions += 1

    def stats(self) -> Dict[str, float]:
        lookups = self.hits + self.misses
        return {
            "size": len(self),
            "capacity": self.capacity,
            "hits": self.hits,
            "misses": self.misses,
            "advances": self.advances,
            "evictions": self.evictions,
            "hit_rate": self.hits / lookups if lookups else 0.0,
        }
