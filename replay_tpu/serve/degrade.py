"""Graceful degradation: the host-side fallback scorer at the ladder's floor.

Under an open breaker or sustained overload the service routes traffic down a
ladder of cheaper modes (``docs/serving.md`` "Overload and degradation"):

1. **primary** — the full path (encode → score / retrieve → rerank). The only
   rung with the bitwise parity contract.
2. **cache_only** — the encode step is skipped: the user's most recent CACHED
   embedding is scored through the existing hidden-scorer hit lane. Bitwise
   identical to a pure cache hit of that state — it *is* one — but the state
   may be stale relative to the request (a just-advanced window's new event is
   recorded in the cache yet unscored until the engine recovers).
3. **fallback** — this module: a pure-host popularity scorer. No device, no
   model, survives anything; answers are generic, not personalized.

Every response carries ``served_by`` naming its rung, so degraded traffic is
visible to clients, the event stream, and ``obs.report``.

The reference serves a dedicated popularity model (``PopRec``) for cold
traffic; here the same ranking doubles as the outage floor — built from
interaction counts (or any score-per-item array) once, then served as
O(k) host gathers per request.

The fleet (``serve/fleet.py``) leans on this rung one more way: with
``ScoringService(cold_miss="fallback")``, a state-less READ for an
UNKNOWN user (no history, no new_items) rides the floor instead of
erroring — the failover setting, where a dead replica's users arrive
downstream with cold caches by construction and a generic answer beats a
``KeyError`` (``served_by == "fallback"`` keeps the path visible).
Interaction-bearing ``new_items`` requests still error: an event that
cannot land is never masked by a success response.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

__all__ = ["DEGRADATION_LADDER", "FallbackScorer"]

# served_by values, best to worst — the order the service walks under duress
DEGRADATION_LADDER = ("primary", "cache_only", "fallback")


class FallbackScorer:
    """Host-side popularity ranking: the degradation ladder's last rung.

    :param item_scores: ``[num_items]`` float scores (e.g. interaction
        counts); item id IS the index. The descending stable ranking is
        precomputed once so serving is a gather, and ties break toward the
        smaller id — deterministic across processes.
    """

    def __init__(self, item_scores: Sequence[float]) -> None:
        scores = np.asarray(item_scores, np.float32)
        if scores.ndim != 1 or scores.size == 0:
            msg = "item_scores must be a non-empty 1-D array"
            raise ValueError(msg)
        self.item_scores = scores
        self.ranking = np.argsort(-scores, kind="stable").astype(np.int64)
        self.served = 0  # bumped by the service per fallback response

    @classmethod
    def from_interactions(
        cls, item_ids: Sequence[int], num_items: int
    ) -> "FallbackScorer":
        """Popularity from raw interaction item ids (training-log counts)."""
        counts = np.bincount(
            np.asarray(item_ids, np.int64), minlength=int(num_items)
        ).astype(np.float32)
        return cls(counts)

    @property
    def num_items(self) -> int:
        return int(self.item_scores.shape[0])

    def score(
        self,
        k: Optional[int] = None,
        candidates: Optional[Sequence[int]] = None,
    ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """``(scores, item_ids)`` in the same shapes the primary path returns.

        ``candidates`` → exact popularity gathers for those ids;
        ``k`` → the top-k popular items; neither → the full popularity vector
        (``item_ids`` None, index IS the id — full-mode convention).
        """
        if candidates is not None:
            ids = np.asarray(candidates, np.int64)
            return self.item_scores[ids], ids
        if k is not None:
            top = self.ranking[: int(k)]
            return self.item_scores[top], top
        return self.item_scores.copy(), None

    def stats(self) -> Dict[str, float]:
        return {"num_items": self.num_items, "served": self.served}
