"""Scoring engine: the pre-compiled executables behind the service.

Two program families, both AOT-compiled at service construction so serving
never traces:

* **encode lanes** — one :class:`~replay_tpu.nn.compiled.CompiledInference`
  per LENGTH bucket (each in ``dynamic_batch_size`` mode, so each length also
  carries the batch-bucket ladder). A request is routed to the smallest length
  bucket holding its window; because the positional table is tail-anchored
  (``nn/agg.py``: shorter inputs take the table's tail) and padded keys are
  masked to exact zeros in the softmax, a narrow-bucket encode is bitwise
  identical to the same window right-aligned at full length — tested in
  ``tests/serve/``.
* **hidden scorers** — one executable per batch bucket scoring CACHED
  last-position hidden states against the catalog (or the compiled slate):
  the pure-cache-hit lane, which skips the transformer entirely.

``outputs="hidden"`` (retrieval mode) drops the full-catalog logits from the
encode programs — candidates come from the MIPS index instead, so the
``[B, |catalog|]`` matmul never runs.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from replay_tpu.nn.compiled import CompiledInference, params_mismatch


def _smallest_covering(sorted_sizes: Sequence[int], n: int) -> int:
    for size in sorted_sizes:
        if size >= n:
            return size
    msg = f"{n} exceeds the largest compiled size {max(sorted_sizes)}"
    raise ValueError(msg)


class ScoringEngine:
    """Routes ``[n, L]`` windows / ``[n, E]`` cached states to compiled buckets."""

    def __init__(
        self,
        model,
        params,
        max_sequence_length: Optional[int] = None,
        length_buckets: Optional[Sequence[int]] = None,
        batch_buckets: Sequence[int] = (1, 8, 64),
        candidates: Optional[np.ndarray] = None,
        feature_name: str = "item_id",
        outputs: str = "both",
    ) -> None:
        if outputs not in ("both", "hidden"):
            msg = "ScoringEngine outputs must be 'both' or 'hidden'"
            raise ValueError(msg)
        self.params = params
        self.max_sequence_length = int(
            max_sequence_length
            if max_sequence_length is not None
            else model.max_sequence_length
        )
        lengths = sorted(set(length_buckets or (self.max_sequence_length,)))
        if lengths[-1] != self.max_sequence_length:
            msg = (
                f"length_buckets must top out at max_sequence_length "
                f"{self.max_sequence_length}, got {lengths}"
            )
            raise ValueError(msg)
        self.length_buckets: Tuple[int, ...] = tuple(lengths)
        self.batch_buckets: Tuple[int, ...] = tuple(sorted(set(batch_buckets)))
        self.outputs = outputs
        self.candidates = (
            np.asarray(candidates, np.int32) if candidates is not None else None
        )
        if self.candidates is not None and outputs == "hidden":
            msg = "a fixed candidate slate needs scoring outputs; use outputs='both'"
            raise ValueError(msg)
        self.embedding_dim = int(model.embedding_dim)

        candidates_count = len(self.candidates) if self.candidates is not None else None
        self._encoders: Dict[int, CompiledInference] = {
            length: CompiledInference.compile(
                model,
                params,
                max_sequence_length=length,
                mode="dynamic_batch_size",
                dynamic_buckets=self.batch_buckets,
                candidates_count=candidates_count,
                feature_name=feature_name,
                outputs=outputs,
            )
            for length in self.length_buckets
        }

        # hidden scorers (skipped in retrieval mode: cached states go straight
        # to the MIPS index, no catalog-wide matmul exists to compile)
        self._hidden_scorers: Dict[int, Any] = {}
        if outputs == "both":
            model_cls = type(model)

            def score_hidden(params, hidden, cands):
                return model.apply(
                    {"params": params},
                    hidden,
                    candidates_to_score=cands,
                    method=model_cls.get_logits,
                )

            for size in self.batch_buckets:
                hidden_spec = jax.ShapeDtypeStruct(
                    (size, self.embedding_dim), jnp.float32
                )
                cand_spec = (
                    jax.ShapeDtypeStruct((candidates_count,), jnp.int32)
                    if candidates_count
                    else None
                )
                executable = (
                    jax.jit(score_hidden)
                    .lower(params, hidden_spec, cand_spec)
                    .compile()
                )
                # params first, as a real program argument — the hot-swap seam
                # (same convention as the CompiledInference encode programs)
                self._hidden_scorers[size] = (
                    lambda p, hidden, cands, _ex=executable: _ex(p, hidden, cands)
                )

        # accounting
        self.encode_calls = 0
        self.encode_rows = 0
        self.encode_slots = 0
        self.hit_calls = 0
        self.hit_rows = 0
        self.hit_slots = 0
        self.encode_failures = 0
        self.hit_failures = 0

    # -- routing ------------------------------------------------------------ #
    def route_length(self, length: int) -> int:
        """Smallest compiled length bucket holding a ``length``-event window."""
        return _smallest_covering(self.length_buckets, max(int(length), 1))

    def batch_bucket(self, rows: int) -> int:
        return _smallest_covering(self.batch_buckets, rows)

    # -- hot swap ----------------------------------------------------------- #
    def validate_params(self, params) -> Optional[str]:
        """Why ``params`` can NOT hot-swap into this engine's executables
        (structure/shape/dtype mismatch vs the lowering pytree — e.g. a grown
        item table), or ``None`` when a zero-recompile swap is legal."""
        return params_mismatch(self.params, params)

    def swap_params(self, params) -> None:
        """Install a new same-shape parameter set into EVERY executable —
        encoders and hidden scorers — without recompiling (params are program
        arguments). Raises ``ValueError`` naming the offending leaf when the
        shapes changed; build a fresh engine for that."""
        mismatch = self.validate_params(params)
        if mismatch is not None:
            msg = (
                f"params cannot hot-swap into the serving executables: "
                f"{mismatch}. A changed catalog shape needs freshly compiled "
                "executables (a new ScoringEngine), not a swap."
            )
            raise ValueError(msg)
        self.params = params
        for compiled in self._encoders.values():
            compiled.swap_params(params)

    # -- execution (serve-worker thread) ------------------------------------ #
    def encode(
        self,
        length_bucket: int,
        item_ids: np.ndarray,
        padding_mask: np.ndarray,
        params=None,
    ):
        """Run the length bucket's executable on ``[n, L_bucket]`` windows.

        Returns ``(logits, hidden)`` in ``"both"`` mode (logits over the
        catalog or the compiled slate) or ``(None, hidden)`` in retrieval
        mode; both cut to the real row count, device-resident. ``params``
        overrides the bound parameter set for this call (the per-dispatch
        generation resolution of the hot-swap path)."""
        compiled = self._encoders[length_bucket]
        rows = item_ids.shape[0]
        try:
            out = compiled(
                item_ids, padding_mask, candidates=self.candidates, params=params
            )
            # async dispatch surfaces device-side failures at materialization,
            # which would otherwise happen at the caller's np.asarray — block
            # here (the worker materializes immediately anyway) so the failure
            # lands in THIS try and the accounting below stays truthful
            out = jax.block_until_ready(out)
        except Exception:
            # failed calls are not credited as served rows/slots (the fill
            # ratio must reflect work that produced scores) but ARE counted —
            # the breaker's raw material
            self.encode_failures += 1
            raise
        self.encode_calls += 1
        self.encode_rows += rows
        self.encode_slots += self.batch_bucket(rows)
        if self.outputs == "both":
            return out
        return None, out

    def score_hidden(self, hidden: np.ndarray, params=None):
        """Score cached ``[n, E]`` hidden states (the pure-hit lane), padded
        up to the nearest batch bucket; device-resident result cut to ``n``.
        ``params`` overrides the bound parameter set for this call."""
        if not self._hidden_scorers:
            msg = "retrieval-mode engine has no hidden scorer (use the pipeline)"
            raise ValueError(msg)
        hidden = np.asarray(hidden, np.float32)
        rows = hidden.shape[0]
        bucket = self.batch_bucket(rows)
        if rows < bucket:
            hidden = np.concatenate(
                [hidden, np.repeat(hidden[:1], bucket - rows, 0)]
            )
        try:
            logits = jax.block_until_ready(
                self._hidden_scorers[bucket](
                    self.params if params is None else params,
                    hidden,
                    self.candidates,
                )
            )
        except Exception:
            self.hit_failures += 1
            raise
        self.hit_calls += 1
        self.hit_rows += rows
        self.hit_slots += bucket
        return logits[:rows]

    def record_ranked_batch(self, rows: int, bucket: int) -> None:
        """Account a retrieval-mode pure-hit batch that bypassed the scorers
        (cached states go straight to the MIPS pipeline) — without this the
        fill ratio would only see the minority encode lane."""
        self.hit_calls += 1
        self.hit_rows += rows
        self.hit_slots += bucket

    def stats(self) -> Dict[str, float]:
        slots = self.encode_slots + self.hit_slots
        rows = self.encode_rows + self.hit_rows
        return {
            "encode_calls": self.encode_calls,
            "encode_rows": self.encode_rows,
            "hit_calls": self.hit_calls,
            "hit_rows": self.hit_rows,
            "encode_failures": self.encode_failures,
            "hit_failures": self.hit_failures,
            "batch_fill_ratio": rows / slots if slots else 0.0,
        }
