"""Serving-resilience exceptions: every way the service says "no" quickly.

The reference's serving runtime fails requests through HTTP status codes; here
the same taxonomy rides :class:`concurrent.futures.Future` exceptions so a
client can branch on WHY a request was refused — and, for the retryable
refusals, on WHEN to come back:

* :class:`RequestShed` — admission control: the lane's bounded queue is full
  (or the breaker-degraded paths were saturated too). Retryable; carries the
  observed queue depth and a ``retry_after_s`` hint.
* :class:`DeadlineExceeded` — the request's end-to-end ``deadline_ms`` expired
  while it was still queued, so the batch builder dropped it BEFORE it could
  burn a device slot (abandoned work never reaches the accelerator).
* :class:`CircuitOpen` — the engine breaker is open and no degraded mode could
  absorb the request. Retryable after ``retry_after_s`` (the breaker's
  remaining open window).
* :class:`ServiceClosed` — the service stopped (or its worker exhausted the
  restart budget); every pending future is failed with this rather than left
  to hang.
* :class:`NoHealthyReplica` — the FLEET-level refusal (``serve/fleet.py``):
  no replica on the user's ring order could take the request (all dead,
  draining, or every retry exhausted). Carries the fleet's health map at
  refusal time.

All subclass :class:`ServeError` (itself a ``RuntimeError``), so
``except ServeError`` catches exactly the service's own refusals while real
engine exceptions — the thing the breaker counts — pass through untouched.
"""

from __future__ import annotations

from typing import Hashable, Optional


class ServeError(RuntimeError):
    """Base class for the scoring service's own request refusals."""


class RequestShed(ServeError):
    """Admission control refused the request: the lane's queue is full.

    :param lane: the saturated lane (as routed — e.g. ``('encode', 16)``).
    :param depth: queue depth observed at refusal.
    :param max_depth: the configured per-lane bound.
    :param retry_after_s: hint — roughly how long until the backlog drains
        enough to admit new work (depth x recent per-batch dispatch time).
    """

    def __init__(
        self,
        lane: Hashable,
        depth: int,
        max_depth: int,
        retry_after_s: Optional[float] = None,
    ) -> None:
        self.lane = lane
        self.depth = int(depth)
        self.max_depth = int(max_depth)
        self.retry_after_s = retry_after_s
        hint = f", retry after ~{retry_after_s:.3f}s" if retry_after_s is not None else ""
        super().__init__(
            f"request shed: lane {lane!r} queue at {depth}/{max_depth}{hint}"
        )


class DeadlineExceeded(ServeError):
    """The request's end-to-end deadline expired before batch build.

    Dropped requests never reach the device: an expired waiter costs queue
    bookkeeping, not a scoring slot.
    """

    def __init__(self, waited_s: float, deadline_s: float) -> None:
        self.waited_s = float(waited_s)
        self.deadline_s = float(deadline_s)
        super().__init__(
            f"deadline exceeded: waited {waited_s * 1000.0:.1f} ms "
            f"of a {deadline_s * 1000.0:.1f} ms budget"
        )


class CircuitOpen(ServeError):
    """The engine breaker is open and no degraded mode could serve this.

    :param retry_after_s: remaining open window before the breaker will
        half-open and admit a probe.
    """

    def __init__(self, retry_after_s: Optional[float] = None) -> None:
        self.retry_after_s = retry_after_s
        hint = f"; retry after ~{retry_after_s:.3f}s" if retry_after_s is not None else ""
        super().__init__(f"scoring engine circuit is open{hint}")


class NoHealthyReplica(ServeError):
    """The fleet router found no replica able to take this request.

    :param replicas: replica ids consulted (the ring membership at refusal).
    :param cause: the last per-replica refusal, when the router got that far
        (e.g. the final :class:`RequestShed` after retries were exhausted).
    """

    def __init__(self, replicas=(), cause: Optional[BaseException] = None) -> None:
        self.replicas = list(replicas)
        self.cause = cause
        detail = f" (last refusal: {cause!r})" if cause is not None else ""
        super().__init__(
            f"no healthy replica among {self.replicas or '<empty fleet>'}{detail}"
        )


class ServiceClosed(ServeError):
    """The service stopped; this request will never be served.

    The message deliberately contains "not running": the micro-batcher's
    pre-resilience contract (``RuntimeError`` matching that phrase) stays
    intact for existing callers.
    """

    def __init__(self, detail: str = "service is not running") -> None:
        super().__init__(detail)
