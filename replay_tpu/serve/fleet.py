"""The serving fleet: N scoring replicas behind a consistent-hash router.

One :class:`~replay_tpu.serve.ScoringService` is one process, one device, one
``UserStateCache`` — a single point of failure that cannot serve millions of
users. :class:`ServingFleet` composes N of them (ROADMAP item 4):

* **routing** — users map to replicas on a :class:`~.router.HashRing`
  (bounded movement: adding/removing a replica remigrates ~1/N of users, so
  the per-user state caches on every OTHER replica stay hot through
  membership changes and rollouts).
* **health** — a monitor thread drives each replica's
  ``healthy → degraded → draining → dead`` state from heartbeats plus the
  gauges the replica already exports (lane depth, breaker state, windowed
  error rate). Every transition is one ``on_replica_health`` event; a death
  additionally emits ``on_failover``.
* **failover** — a dead home replica's users are served by the next replica
  on their ring order. The rerouted users' caches are COLD there by
  construction; with ``ScoringService(cold_miss="fallback")`` those requests
  ride the PR-9 degradation ladder (visible in ``served_by``) instead of
  erroring, and they return home — caches intact — when the replica revives.
* **hedging** — an idempotent request still unanswered after a p99-derived
  delay races a second replica; the first answer wins and the loser is
  cancelled through the existing future-cancel path (the batch builder skips
  cancelled waiters before any device work).
* **router-level admission control** — a replica's
  :class:`~replay_tpu.serve.errors.RequestShed` / ``CircuitOpen`` refusal is
  retried with capped exponential backoff that honors ``retry_after_s``
  (:class:`~.router.BackoffPolicy`) — but ONLY for idempotent requests
  (``new_items`` traffic mutates the home cache at submit; re-sending it
  would double-land the interaction), and an ANSWER is never retried: a
  degraded response (``served_by != "primary"``) is the ladder working, not
  a failure to shop around.
* **drain protocol** — :meth:`drain` stops NEW traffic to a replica and waits
  for its lanes to empty (zero orphaned waiters), the caller hot-swaps
  weights through the PR-14 promotion path, :meth:`rejoin` restores it.
  :meth:`rolling_swap` runs that end-to-end across the fleet: a zero-downtime
  fleet-wide rollout.

The fleet is deliberately jax-free and duck-typed over its replicas (the
``submit/heartbeat/stats/start/close`` surface), so the routing, failover,
hedging and drain logic is host-only-testable (``tests/serve/test_router.py``)
exactly like the micro-batcher and breaker underneath it.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from concurrent.futures import CancelledError, Future
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Any, Callable, Dict, Hashable, List, Mapping, Optional, Sequence, Tuple

from replay_tpu.obs import TraceContext, Tracer, TrainerEvent

from .errors import CircuitOpen, NoHealthyReplica, RequestShed, ServiceClosed
from .futures import safe_fail, safe_set_result
from .router import BackoffPolicy, HashRing, ReplicaHealth

__all__ = ["ReplicaHandle", "ServingFleet"]

# latency histogram bounds in ms (the p99-derived hedge delay's material)
_LATENCY_MS_BUCKETS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
    2500.0, 5000.0,
)


class ReplicaHandle:
    """One fleet slot: a scoring service, its ring id, and its health."""

    def __init__(self, replica_id: str, service: Any, clock: Callable[[], float]) -> None:
        self.replica_id = replica_id
        self.service = service
        self.health = ReplicaHealth(replica_id, clock=clock)
        # last heartbeat's cumulative counters — the monitor's windowed
        # error-rate material (cumulative rates would never recover)
        self.last_requests = 0.0
        self.last_errors = 0.0
        self.routed = 0
        self.answered = 0
        # per-replica resilience accounting (stats()["per_replica"], rendered
        # by obs.report): hedges LANDED here as the racing twin, wins where
        # this replica's hedge answered first, cancels where its twin lost,
        # and retries this replica's refusals caused
        self.hedges = 0
        self.hedge_wins = 0
        self.hedge_cancelled = 0
        self.retries = 0


class _Flight:
    """One client request's in-flight state across primaries/hedges/retries."""

    __slots__ = (
        "user_id", "kwargs", "client", "idempotent", "home", "attempt",
        "tried", "inflight", "scheduled", "retry_scheduled", "failure",
        "hedged", "hedge_replica", "submitted_at", "trace", "trace_t0", "lock",
    )

    def __init__(self, user_id, kwargs, client, idempotent, home, submitted_at,
                 trace=None, trace_t0=0.0):
        self.user_id = user_id
        self.kwargs = kwargs
        self.client = client
        self.idempotent = idempotent
        self.home = home
        self.attempt = 0
        self.tried: List[str] = []
        self.inflight: Dict[Future, str] = {}
        self.scheduled = 0  # timers (hedge/retry) not yet fired
        self.retry_scheduled = False  # at most ONE retry timer per flight
        self.failure: Optional[BaseException] = None
        self.hedged = False
        self.hedge_replica: Optional[str] = None  # who the hedge raced on
        self.submitted_at = submitted_at
        # distributed-trace identity: a TraceContext minted at admission when
        # the fleet tracer is on (None otherwise — the disabled hot path
        # carries no per-request trace state), and the router-tracer-relative
        # admission timestamp anchoring the root "request" span
        self.trace: Optional[TraceContext] = trace
        self.trace_t0 = trace_t0
        self.lock = threading.Lock()


class ServingFleet:
    """N scoring replicas behind a consistent-hash router with failover.

    :param replicas: ``{replica_id: service}`` (or a sequence, auto-named
        ``r0..rN``). A "service" is anything with the ``ScoringService``
        surface: ``submit(user_id, ...) -> Future``, ``heartbeat()``,
        ``start()``, ``close()``, ``stats()`` and (for :meth:`drain`) a
        ``batcher.idle``/``queued_depth`` view.
    :param vnodes: hash-ring virtual nodes per replica (see :class:`HashRing`).
    :param hedge_ms: hedge delay. ``None`` (default) derives it from the
        fleet's own observed p99 (never below ``hedge_floor_ms``); ``0``
        disables hedging.
    :param backoff: router-level retry policy for shed/circuit-open refusals
        of idempotent requests; ``None`` builds :class:`BackoffPolicy`
        defaults. ``BackoffPolicy(max_retries=0)`` disables retries.
    :param heartbeat_interval_s: monitor cadence. ``None`` starts NO monitor
        thread — callers (tests, drivers) invoke :meth:`poll` themselves.
    :param heartbeat_misses: consecutive failed heartbeats before a replica
        is declared dead.
    :param degrade_depth_fraction: lane backlog (queued / max_depth) beyond
        which a replica is marked degraded.
    :param degrade_error_rate: windowed error rate beyond which a replica is
        marked degraded (evaluated only on windows with >= 8 requests).
    :param logger: any :class:`~replay_tpu.obs.RunLogger`; receives
        ``on_fleet_start`` / ``on_replica_health`` / ``on_failover`` /
        ``on_hedge`` / ``on_fleet_end``.
    :param tracer: the ROUTER's :class:`~replay_tpu.obs.Tracer` (the "router"
        track of the merged fleet trace). When enabled, every :meth:`submit`
        mints a :class:`~replay_tpu.obs.TraceContext` and propagates it to the
        replica (``service.submit(..., _trace=...)`` as pure JSON) on every
        launch — primary, hedge and retry alike — while the router records its
        own hops (``route`` / ``hedge_wait`` / ``backoff_wait`` /
        ``failover_reroute`` / ``hedge_cancel``) and the root ``request`` span
        keyed by the same trace_id. ``None`` (default) disables tracing: no
        context is minted, no kwarg injected — the hot path is unchanged.
    """

    def __init__(
        self,
        replicas: Any,
        vnodes: int = 64,
        hedge_ms: Optional[float] = None,
        hedge_floor_ms: float = 20.0,
        backoff: Optional[BackoffPolicy] = None,
        heartbeat_interval_s: Optional[float] = 0.25,
        heartbeat_misses: int = 3,
        degrade_depth_fraction: float = 0.75,
        degrade_error_rate: float = 0.5,
        logger=None,
        tracer: Optional[Tracer] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if isinstance(replicas, Mapping):
            named = dict(replicas)
        else:
            named = {f"r{i}": service for i, service in enumerate(replicas)}
        if not named:
            msg = "a fleet needs at least one replica"
            raise ValueError(msg)
        self._clock = clock
        self.logger = logger
        self.tracer = tracer if tracer is not None else Tracer(enabled=False)
        self.handles: Dict[str, ReplicaHandle] = {
            str(rid): ReplicaHandle(str(rid), service, clock)
            for rid, service in named.items()
        }
        self.ring = HashRing(tuple(self.handles), vnodes=vnodes)
        self.hedge_ms = hedge_ms
        self.hedge_floor_ms = float(hedge_floor_ms)
        self.backoff = backoff if backoff is not None else BackoffPolicy()
        self.heartbeat_interval_s = heartbeat_interval_s
        self.heartbeat_misses = int(heartbeat_misses)
        self.degrade_depth_fraction = float(degrade_depth_fraction)
        self.degrade_error_rate = float(degrade_error_rate)

        self._lock = threading.Lock()  # counters
        self._health_lock = threading.Lock()  # every health transition
        self._requests = 0
        self._answered = 0
        self._errors = 0
        self._reroutes = 0
        self._retries = 0
        self._hedges = 0
        self._hedge_wins = 0
        self._hedge_cancelled = 0
        self._failovers = 0
        self._no_healthy_refusals = 0
        from replay_tpu.obs.metrics import Histogram

        self._latency_ms = Histogram(_LATENCY_MS_BUCKETS)

        # one scheduler thread for hedge timers and retry backoff: a heap of
        # (due, seq, fn) under a condition — bounded threads no matter the
        # request rate
        self._timers: List[Tuple[float, int, Callable[[], None]]] = []
        self._timer_seq = itertools.count()
        self._timer_wake = threading.Condition()
        self._scheduler: Optional[threading.Thread] = None
        self._monitor: Optional[threading.Thread] = None
        self._running = False

    # -- lifecycle ----------------------------------------------------------- #
    def start(self) -> "ServingFleet":
        if self._running:
            return self
        self._running = True
        for handle in self.handles.values():
            handle.service.start()
        self._scheduler = threading.Thread(
            target=self._run_timers, name="fleet-scheduler", daemon=True
        )
        self._scheduler.start()
        if self.heartbeat_interval_s is not None:
            self._monitor = threading.Thread(
                target=self._run_monitor, name="fleet-monitor", daemon=True
            )
            self._monitor.start()
        self._emit(
            "on_fleet_start",
            {
                "replicas": sorted(self.handles),
                "vnodes": self.ring.vnodes,
                "hedge_ms": self.hedge_ms,
                "hedge_floor_ms": self.hedge_floor_ms,
                "max_retries": self.backoff.max_retries,
                "heartbeat_interval_s": self.heartbeat_interval_s,
            },
        )
        return self

    def close(self) -> None:
        """Stop the monitor/scheduler and close every replica. Replica
        ``close()`` resolves each service's own pending futures (the PR-9
        no-orphaned-waiters contract), so fleet shutdown hangs nothing."""
        if not self._running:
            return
        self._running = False
        with self._timer_wake:
            self._timer_wake.notify_all()
        for thread in (self._monitor, self._scheduler):
            if thread is not None:
                thread.join(timeout=5.0)
        self._monitor = self._scheduler = None
        # fire whatever the scheduler did not get to (or left past the join
        # timeout) on THIS thread: a hedge/retry timer scheduled before the
        # shutdown must still run so its flight's scheduled-count drops and
        # the client resolves — timers are no-ops or fast-fails by now
        # (_running is False), never new work
        while True:
            with self._timer_wake:
                if not self._timers:
                    break
                _, _, fn = heapq.heappop(self._timers)
            try:
                fn()
            except Exception:  # noqa: BLE001 — drain must complete
                pass
        for handle in self.handles.values():
            handle.service.close()
        self._emit("on_fleet_end", self.stats())

    def __enter__(self) -> "ServingFleet":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.close()

    # -- client API ---------------------------------------------------------- #
    def submit(
        self,
        user_id: Hashable,
        history: Optional[Sequence[int]] = None,
        new_items: Sequence[int] = (),
        k: Optional[int] = None,
        candidates: Optional[Sequence[int]] = None,
        deadline_ms: Optional[float] = None,
    ) -> "Future":
        """Route one request to the user's replica; resolves to that
        replica's :class:`~replay_tpu.serve.ScoreResponse` with ``.replica``
        stamped. Never blocks, never hangs: refusals fail the future with the
        serve taxonomy (:class:`NoHealthyReplica` when no replica can take
        the request at all)."""
        client: "Future" = Future()
        kwargs = {
            "history": history,
            "new_items": tuple(new_items),
            "k": k,
            "candidates": candidates,
            "deadline_ms": deadline_ms,
        }
        with self._lock:
            self._requests += 1
        # trace admission: mint the context BEFORE routing so the hash lookup
        # itself is a traced hop. Tracing off mints nothing — None everywhere
        trace: Optional[TraceContext] = None
        trace_t0 = 0.0
        if self.tracer.enabled:
            trace = TraceContext.mint()
            trace_t0 = self.tracer.now()
        order = self.ring.preference(user_id)
        flight = _Flight(
            user_id=user_id,
            kwargs=kwargs,
            client=client,
            idempotent=not new_items,
            home=order[0] if order else None,
            submitted_at=self._clock(),
            trace=trace,
            trace_t0=trace_t0,
        )
        target = self._pick_target(order, skip=())
        if trace is not None:
            # the replica-bound context rides in flight.kwargs, so EVERY
            # launch (primary, hedge, retry) forwards it — as pure JSON,
            # the same payload a future socket boundary would carry
            kwargs["_trace"] = trace.child("route").to_json()
            self.tracer.add_span(
                "route", trace_t0, self.tracer.now() - trace_t0,
                trace_id=trace.trace_id, user=str(user_id),
                home=flight.home, target=target,
            )
        if target is None:
            with self._lock:
                self._no_healthy_refusals += 1
                self._errors += 1
            self._safe_fail(client, NoHealthyReplica(list(self.handles)))
            return client
        if target != flight.home:
            with self._lock:
                self._reroutes += 1
            if trace is not None:
                self.tracer.add_span(
                    "failover_reroute", self.tracer.now(), 0.0,
                    trace_id=trace.trace_id, home=flight.home, target=target,
                )
        self._launch(flight, target, hedge_eligible=True)
        # a client-side give-up (score(timeout=...) cancels) propagates to
        # the in-flight replica requests, so the batch builder skips them
        # before any device work — the single-service cancel path, one
        # level up
        client.add_done_callback(lambda f: self._propagate_cancel(flight, f))
        return client

    def _propagate_cancel(self, flight: _Flight, client: "Future") -> None:
        if not client.cancelled():
            return
        with flight.lock:
            pending = [inner for inner in flight.inflight if not inner.done()]
        for inner in pending:
            inner.cancel()

    def score(self, user_id: Hashable, timeout: Optional[float] = 60.0, **kwargs):
        """Synchronous :meth:`submit` (mirrors ``ScoringService.score``)."""
        if timeout is not None and "deadline_ms" not in kwargs:
            kwargs["deadline_ms"] = timeout * 1000.0
        future = self.submit(user_id, **kwargs)
        try:
            return future.result(timeout=timeout)
        except FutureTimeoutError:
            future.cancel()
            raise

    # -- routing ------------------------------------------------------------- #
    def _pick_target(
        self, order: Sequence[str], skip: Sequence[str]
    ) -> Optional[str]:
        """First usable replica in the user's ring order: the home replica if
        it takes traffic (healthy OR degraded — home traffic sticks to warm
        caches as long as the replica answers at all); otherwise the first
        HEALTHY replica downstream (failover never piles onto a degraded
        one), falling back to any traffic-taking replica when nothing is
        fully healthy."""
        with self._health_lock:
            usable = [
                rid for rid in order
                if rid not in skip and self.handles[rid].health.takes_traffic
            ]
            if not usable:
                return None
            if order and usable and usable[0] == order[0]:
                return usable[0]
            for rid in usable:
                if self.handles[rid].health.takes_failover:
                    return rid
            return usable[0]

    def _hedge_target(self, flight: _Flight, primary: str) -> Optional[str]:
        order = self.ring.preference(flight.user_id)
        with self._health_lock:
            for rid in order:
                if rid != primary and self.handles[rid].health.takes_failover:
                    return rid
        return None

    # -- dispatch ------------------------------------------------------------ #
    def _launch(self, flight: _Flight, replica_id: str, hedge_eligible: bool) -> None:
        handle = self.handles[replica_id]
        flight.tried.append(replica_id)
        with self._lock:
            handle.routed += 1
        try:
            inner = handle.service.submit(flight.user_id, **flight.kwargs)
        except Exception as exc:  # noqa: BLE001 — a dead replica object
            self._on_refusal(flight, replica_id, exc)
            return
        with flight.lock:
            flight.inflight[inner] = replica_id
        inner.add_done_callback(
            lambda f, rid=replica_id: self._on_inner_done(flight, rid, f)
        )
        # a racer (the primary answering, a client give-up) may have resolved
        # the flight between the pre-launch check and this registration — its
        # loser sweep ran before this inner existed, so cancel it here or the
        # duplicate runs full device work
        if flight.client.done():
            inner.cancel()
        if hedge_eligible and flight.idempotent:
            delay_ms = self._hedge_delay_ms()
            if delay_ms is not None:
                self._schedule_flight(
                    delay_ms / 1000.0, flight, lambda: self._fire_hedge(flight, replica_id)
                )

    def _hedge_delay_ms(self) -> Optional[float]:
        if self.hedge_ms is not None:
            return float(self.hedge_ms) if self.hedge_ms > 0 else None
        with self._lock:
            p99 = self._latency_ms.quantile(0.99)
        if p99 is None:
            return self.hedge_floor_ms
        return max(float(p99), self.hedge_floor_ms)

    def _fire_hedge(self, flight: _Flight, primary: str) -> None:
        if flight.client.done():
            self._maybe_finalize(flight)
            return
        with flight.lock:
            primary_pending = any(not f.done() for f in flight.inflight)
            already_hedged = flight.hedged
        if not primary_pending or already_hedged:
            self._maybe_finalize(flight)
            return
        target = self._hedge_target(flight, primary)
        if target is None:
            self._maybe_finalize(flight)
            return
        with flight.lock:
            flight.hedged = True
            flight.hedge_replica = target
        with self._lock:
            self._hedges += 1
            target_handle = self.handles.get(target)
            if target_handle is not None:
                target_handle.hedges += 1
        if flight.trace is not None:
            # the window the primary was given before the race began —
            # admission to hedge launch, on the router track
            now = self.tracer.now()
            self.tracer.add_span(
                "hedge_wait", flight.trace_t0, now - flight.trace_t0,
                trace_id=flight.trace.trace_id, primary=primary, hedge=target,
            )
        self._emit(
            "on_hedge",
            {"user_id": str(flight.user_id), "primary": primary, "hedge": target},
        )
        self._launch(flight, target, hedge_eligible=False)
        self._maybe_finalize(flight)

    def _on_inner_done(self, flight: _Flight, replica_id: str, inner: "Future") -> None:
        try:
            exc = inner.exception()
        except CancelledError:
            # the loser we cancelled (or a client-side give-up): accounted at
            # cancel time, nothing to resolve here
            with flight.lock:
                flight.inflight.pop(inner, None)
            self._maybe_finalize(flight)
            return
        with flight.lock:
            flight.inflight.pop(inner, None)
        if exc is None:
            self._on_answer(flight, replica_id, inner.result())
            return
        self._on_refusal(flight, replica_id, exc)

    def _on_answer(self, flight: _Flight, replica_id: str, response) -> None:
        response.replica = replica_id
        if flight.trace is not None:
            # stamp the winning answer with its trace id (like ``.replica``):
            # a chaos probe's slow failover answer links straight to its trace
            response.trace_id = flight.trace.trace_id
        if not self._safe_set_result(flight.client, response):
            return  # a racing hedge already won (or the client gave up)
        handle = self.handles.get(replica_id)
        now = self._clock()
        with self._lock:
            self._answered += 1
            if handle is not None:
                handle.answered += 1
            self._latency_ms.observe(
                (now - flight.submitted_at) * 1000.0,
                exemplar=flight.trace.trace_id if flight.trace is not None else None,
            )
            # a win is the HEDGE replica answering — not whoever happened to
            # be tried last (a post-hedge backoff retry answering is a retry
            # win, and the hedge itself lost)
            if flight.hedged and replica_id == flight.hedge_replica:
                self._hedge_wins += 1
                if handle is not None:
                    handle.hedge_wins += 1
        if flight.trace is not None:
            # the root span of the whole request: admission → winning answer.
            # Its duration is the denominator of the report's tail attribution
            # (every hop span sharing this trace_id is a numerator slice), and
            # ``served_by`` names the degradation-ladder rung that answered
            self.tracer.add_span(
                "request", flight.trace_t0, self.tracer.now() - flight.trace_t0,
                trace_id=flight.trace.trace_id, user=str(flight.user_id),
                replica=replica_id,
                served_by=getattr(response, "served_by", None),
                served_from=getattr(response, "served_from", None),
                hedged=flight.hedged, attempts=flight.attempt,
            )
        # cancel the losers through the existing future-cancel path: a still-
        # queued twin is skipped at batch build before any device work
        with flight.lock:
            losers = [
                (f, rid) for f, rid in flight.inflight.items() if not f.done()
            ]
        for loser, loser_rid in losers:
            if loser.cancel():
                with self._lock:
                    self._hedge_cancelled += 1
                    loser_handle = self.handles.get(loser_rid)
                    if loser_handle is not None:
                        loser_handle.hedge_cancelled += 1
                if flight.trace is not None:
                    self.tracer.add_span(
                        "hedge_cancel", self.tracer.now(), 0.0,
                        trace_id=flight.trace.trace_id, replica=loser_rid,
                    )

    def _on_refusal(self, flight: _Flight, replica_id: str, exc: BaseException) -> None:
        retryable = isinstance(exc, (RequestShed, CircuitOpen, ServiceClosed))
        schedule_retry = False
        delay = 0.0
        with flight.lock:
            # the retry decision is one atomic read-modify-write: a primary
            # and a hedge twin refusing concurrently must not both pass the
            # budget check at the same attempt value (doubled retries, lost
            # increments) — and a closing fleet must not schedule into a
            # scheduler that is shutting down (the timer would never fire
            # and the client would hang forever)
            if (
                retryable
                and flight.idempotent
                and self._running
                and not flight.retry_scheduled
                and not flight.client.done()
                and not self.backoff.exhausted(flight.attempt)
            ):
                retry_after = getattr(exc, "retry_after_s", None)
                delay = self.backoff.delay(flight.attempt, retry_after_s=retry_after)
                flight.attempt += 1
                flight.retry_scheduled = True
                schedule_retry = True
            else:
                flight.failure = exc
        if schedule_retry:
            with self._lock:
                self._retries += 1
                refusing = self.handles.get(replica_id)
                if refusing is not None:
                    refusing.retries += 1
            if flight.trace is not None:
                # the backoff window is known NOW (the scheduler fires exactly
                # ``delay`` later): record it as a span so the wait the
                # refusal bought is visible on the request's timeline
                self.tracer.add_span(
                    "backoff_wait", self.tracer.now(), delay,
                    trace_id=flight.trace.trace_id, replica=replica_id,
                    attempt=flight.attempt, error=type(exc).__name__,
                )
            self._schedule_flight(delay, flight, lambda: self._fire_retry(flight, exc))
            return
        self._maybe_finalize(flight)

    def _fire_retry(self, flight: _Flight, previous: BaseException) -> None:
        with flight.lock:
            flight.retry_scheduled = False
        if flight.client.done():
            self._maybe_finalize(flight)
            return
        # a replica that refused once is skipped — unless it is the only one
        # left, in which case honoring its retry_after_s and coming back IS
        # the plan (the single-replica degenerate fleet)
        order = self.ring.preference(flight.user_id)
        target = self._pick_target(order, skip=flight.tried)
        if target is None:
            target = self._pick_target(order, skip=())
        if target is None:
            with flight.lock:
                flight.failure = NoHealthyReplica(list(self.handles), cause=previous)
            self._maybe_finalize(flight)
            return
        if target != flight.home:
            with self._lock:
                self._reroutes += 1
            if flight.trace is not None:
                self.tracer.add_span(
                    "failover_reroute", self.tracer.now(), 0.0,
                    trace_id=flight.trace.trace_id, home=flight.home,
                    target=target, retry=True,
                )
        self._launch(flight, target, hedge_eligible=False)
        self._maybe_finalize(flight)

    def _maybe_finalize(self, flight: _Flight) -> None:
        """Fail the client once nothing can still answer it: no in-flight
        inner future, no scheduled hedge/retry, and a recorded failure."""
        with flight.lock:
            if flight.client.done():
                return
            if flight.inflight or flight.scheduled:
                return
            failure = flight.failure
        if failure is not None and self._safe_fail(flight.client, failure):
            with self._lock:
                self._errors += 1

    # -- scheduler ------------------------------------------------------------ #
    def _schedule_flight(self, delay_s: float, flight: _Flight, fn: Callable[[], None]) -> None:
        with flight.lock:
            flight.scheduled += 1

        def fire() -> None:
            with flight.lock:
                flight.scheduled -= 1
            fn()

        self._schedule(delay_s, fire)

    def _schedule(self, delay_s: float, fn: Callable[[], None]) -> None:
        due = self._clock() + max(float(delay_s), 0.0)
        with self._timer_wake:
            if self._running:
                heapq.heappush(self._timers, (due, next(self._timer_seq), fn))
                self._timer_wake.notify()
                return
        # closing: no scheduler will ever fire this — run it inline (by now
        # every path it takes is a fast fail/no-op), so its flight's
        # scheduled-count drops and the client can resolve
        try:
            fn()
        except Exception:  # noqa: BLE001 — resolution is best-effort here
            pass

    def _run_timers(self) -> None:
        while True:
            with self._timer_wake:
                if not self._running and not self._timers:
                    return
                now = self._clock()
                if self._timers and self._timers[0][0] <= now:
                    _, _, fn = heapq.heappop(self._timers)
                else:
                    timeout = (
                        self._timers[0][0] - now if self._timers
                        else (0.1 if not self._running else None)
                    )
                    self._timer_wake.wait(timeout=timeout)
                    continue
            try:
                fn()
            except Exception:  # noqa: BLE001 — a timer must not kill the loop
                pass

    # -- health monitor ------------------------------------------------------- #
    def _run_monitor(self) -> None:
        while self._running:
            try:
                self.poll()
            except Exception:  # noqa: BLE001 — a sweep must never kill the
                pass  # monitor; the next interval retries
            time.sleep(self.heartbeat_interval_s)

    def poll(self) -> None:
        """One health sweep over every replica (the monitor thread's body,
        public so tests and drivers can run health deterministically).

        Race-safe against concurrent :meth:`drain`/:meth:`rejoin`: every
        transition here is CONDITIONAL on the state the sweep observed
        (``expected=``) — if an operator moved the replica meanwhile (e.g.
        into ``draining`` mid-sweep), the gauge-driven transition is simply
        dropped rather than applied to the wrong state or raised on.
        """
        for handle in self.handles.values():
            heartbeat = None
            try:
                heartbeat = handle.service.heartbeat()
            except Exception:  # noqa: BLE001 — an unreachable replica
                heartbeat = None
            with self._health_lock:
                observed = handle.health.state
            alive = bool(heartbeat and heartbeat.get("live"))
            if not alive:
                handle.health.consecutive_heartbeat_misses += 1
                if (
                    handle.health.consecutive_heartbeat_misses >= self.heartbeat_misses
                    and observed != "dead"
                ):
                    self._transition(handle, "dead", "heartbeat", expected=observed)
                continue
            handle.health.consecutive_heartbeat_misses = 0
            if observed == "dead":
                # revival: the ring never dropped it, so its users (and their
                # still-cached states) come straight back. The error-window
                # counters re-anchor to the CURRENT totals — the dying burst
                # must not be judged as the freshly-healthy replica's first
                # window (it would re-degrade it on stale history)
                handle.last_requests = float(heartbeat.get("requests") or 0.0)
                handle.last_errors = float(heartbeat.get("errors") or 0.0)
                self._transition(handle, "healthy", "revived", expected=observed)
                continue
            reason = self._degrade_reason(handle, heartbeat)
            if observed == "draining":
                continue  # drain/rejoin are operator-driven, not gauge-driven
            if reason and observed == "healthy":
                self._transition(handle, "degraded", reason, expected=observed)
            elif not reason and observed == "degraded":
                self._transition(handle, "healthy", "recovered", expected=observed)

    def _degrade_reason(self, handle: ReplicaHandle, heartbeat: Mapping[str, Any]) -> Optional[str]:
        """The replica's own exporter gauges, folded into one verdict. The
        error-rate window counters advance on EVERY call — including ones
        that return a breaker/lane-depth verdict — so a later error-rate
        evaluation never judges a window stretching back through an entire
        breaker-open episode."""
        requests = float(heartbeat.get("requests") or 0.0)
        errors = float(heartbeat.get("errors") or 0.0)
        window_requests = requests - handle.last_requests
        window_errors = errors - handle.last_errors
        handle.last_requests = requests
        handle.last_errors = errors
        breaker = heartbeat.get("breaker_state")
        if breaker and breaker != "closed":
            return f"breaker_{breaker}"
        queued = heartbeat.get("queued")
        max_depth = heartbeat.get("max_depth")
        if queued is not None and max_depth:
            if float(queued) >= self.degrade_depth_fraction * float(max_depth):
                return "lane_depth"
        if window_requests >= 8 and window_errors / window_requests > self.degrade_error_rate:
            return "error_rate"
        return None

    def _transition(
        self,
        handle: ReplicaHandle,
        to: str,
        reason: str,
        expected: Optional[str] = None,
    ) -> None:
        """Apply one health transition under the health lock. ``expected``
        makes it conditional: when the replica's state is no longer what the
        caller decided on (a concurrent drain/rejoin won the race), the
        transition is dropped — gauge-driven sweeps must never override an
        operator's move or trip the legality table on a stale read."""
        with self._health_lock:
            if expected is not None and handle.health.state != expected:
                return
            changed = handle.health.transition(to, reason)
        if not changed:
            return
        record = handle.health.transitions[-1]
        self._emit(
            "on_replica_health",
            {
                "replica": handle.replica_id,
                "from": record["from"],
                "to": to,
                "reason": reason,
            },
        )
        if to == "dead":
            with self._lock:
                self._failovers += 1
            self._emit(
                "on_failover",
                {
                    "replica": handle.replica_id,
                    "reason": reason,
                    # ~the slice of users now served downstream (consistent
                    # hashing: one replica's arcs, not a full reshuffle)
                    "users_fraction": 1.0 / max(len(self.handles), 1),
                },
            )

    # -- drain / rollout ------------------------------------------------------ #
    def drain(self, replica_id: str, timeout_s: float = 30.0) -> bool:
        """Stop routing NEW traffic to ``replica_id`` and wait for its lanes
        to empty (queued AND in-flight). Returns whether it fully drained
        within ``timeout_s`` — either way no waiter is orphaned: undrained
        work still resolves through the replica's own dispatch/close path."""
        handle = self.handles[replica_id]
        self._transition(handle, "draining", "drain")
        deadline = self._clock() + float(timeout_s)
        while self._clock() < deadline:
            if self._replica_idle(handle):
                return True
            time.sleep(0.002)
        return self._replica_idle(handle)

    @staticmethod
    def _replica_idle(handle: ReplicaHandle) -> bool:
        batcher = getattr(handle.service, "batcher", None)
        if batcher is None:
            return True
        idle = getattr(batcher, "idle", None)
        if idle is not None:
            return bool(idle)
        return batcher.queued_depth() == 0

    def rejoin(self, replica_id: str) -> None:
        """Return a drained (or revived-from-drain) replica to service."""
        self._transition(self.handles[replica_id], "healthy", "rejoin")

    def drain_and_swap(
        self,
        replica_id: str,
        params,
        label: str = "",
        pipeline=None,
        timeout_s: float = 30.0,
    ) -> Dict[str, Any]:
        """The zero-downtime rollout step for ONE replica: drain → publish +
        promote (the PR-14 hot-swap path: a pointer move for same-shape
        params) → rejoin. The rest of the fleet keeps serving throughout —
        the drained replica's users ride their failover order meanwhile."""
        handle = self.handles[replica_id]
        drained = self.drain(replica_id, timeout_s=timeout_s)
        try:
            generation = handle.service.publish_candidate(
                params, label=label or f"fleet-swap-{replica_id}", pipeline=pipeline
            )
            handle.service.promote(generation)
        except Exception:
            # a failed swap must not strand the replica out of rotation:
            # restore traffic on the OLD generation (zero downtime means the
            # rollout fails, not the replica) and surface the error
            self.rejoin(replica_id)
            raise
        self.rejoin(replica_id)
        return {
            "replica": replica_id,
            "drained": bool(drained),
            "generation": int(generation),
        }

    def rolling_swap(
        self,
        params,
        label: str = "",
        pipeline_factory: Optional[Callable[[str], Any]] = None,
        timeout_s: float = 30.0,
    ) -> List[Dict[str, Any]]:
        """Fleet-wide zero-downtime rollout: :meth:`drain_and_swap` each
        replica in turn (one out of rotation at a time — the fleet never
        loses more than one replica's capacity to the rollout). DEAD
        replicas are skipped, not drained (an illegal dead→draining
        transition would abort the rollout mid-fleet): a skipped replica
        revives on its OLD generation and the operator re-runs the swap for
        it once it is back."""
        results = []
        for replica_id in sorted(self.handles):
            with self._health_lock:
                state = self.handles[replica_id].health.state
            if state == "dead":
                results.append({"replica": replica_id, "skipped": "dead"})
                continue
            pipeline = pipeline_factory(replica_id) if pipeline_factory else None
            results.append(
                self.drain_and_swap(
                    replica_id, params, label=label, pipeline=pipeline,
                    timeout_s=timeout_s,
                )
            )
        return results

    # -- accounting ----------------------------------------------------------- #
    def health(self) -> Dict[str, str]:
        with self._health_lock:
            return {rid: handle.health.state for rid, handle in self.handles.items()}

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            p50 = self._latency_ms.quantile(0.5)
            p99 = self._latency_ms.quantile(0.99)
            out = {
                "replicas": len(self.handles),
                "requests": self._requests,
                "answered": self._answered,
                "errors": self._errors,
                "reroutes": self._reroutes,
                "retries": self._retries,
                "hedges": self._hedges,
                "hedge_wins": self._hedge_wins,
                "hedge_cancelled": self._hedge_cancelled,
                "failovers": self._failovers,
                "no_healthy_refusals": self._no_healthy_refusals,
                "reroute_rate": self._reroutes / self._requests if self._requests else 0.0,
                "error_rate": self._errors / self._requests if self._requests else 0.0,
                "p50_ms": p50,
                "p99_ms": p99,
                "per_replica": {
                    rid: {
                        "routed": handle.routed,
                        "answered": handle.answered,
                        "hedges": handle.hedges,
                        "hedge_wins": handle.hedge_wins,
                        "hedge_cancelled": handle.hedge_cancelled,
                        "retries": handle.retries,
                    }
                    for rid, handle in self.handles.items()
                },
                # slowest-N answered requests with their trace ids (the
                # exemplar store riding the latency histogram) — the
                # report's / bench record's link from "p99 is slow" to the
                # exact traces that made it slow
                "latency_exemplars": [
                    {"latency_ms": e["value"], "trace_id": e["trace_id"]}
                    for e in self._latency_ms.exemplars()
                ],
            }
        with self._health_lock:
            for rid, handle in self.handles.items():
                out["per_replica"][rid].update(
                    {
                        "health": handle.health.state,
                        "health_reason": handle.health.reason,
                        "health_transitions": handle.health.transition_count,
                    }
                )
        # the quality plane, one level up (obs.quality): replicas that carry a
        # QualityMonitor surface their pure-JSON snapshots, plus the fleet-
        # level join-weighted online hitrate and worst drift PSI — the fleet
        # analog of the single-service stats()["quality"] block. In-process
        # replicas only: a remote replica's quality rides its own /snapshot
        per_replica_quality: Dict[str, Any] = {}
        for rid, handle in self.handles.items():
            monitor = getattr(handle.service, "quality", None)
            if monitor is None:
                continue
            try:
                per_replica_quality[rid] = monitor.snapshot()
            except Exception:  # noqa: BLE001 — telemetry must not fail stats
                continue
        if per_replica_quality:
            joins = 0
            hits = 0.0
            psi_values = []
            for snap in per_replica_quality.values():
                stable = (snap.get("roles") or {}).get("stable") or {}
                replica_joins = int(stable.get("joins") or 0)
                hitrate = stable.get("online_hitrate_cum")
                if replica_joins and hitrate is not None:
                    joins += replica_joins
                    hits += float(hitrate) * replica_joins
                psi = (snap.get("drift") or {}).get("max")
                if psi is not None:
                    psi_values.append(float(psi))
            out["quality"] = {
                "per_replica": per_replica_quality,
                "joins": joins,
                "online_hitrate_cum": hits / joins if joins else None,
                "drift_psi_max": max(psi_values) if psi_values else None,
            }
        return out

    # -- helpers -------------------------------------------------------------- #
    def _emit(self, event: str, payload: Dict[str, Any]) -> None:
        if self.logger is not None:
            self.logger.log_event(TrainerEvent(event=event, payload=payload))

    # shared with ScoringService: serve.futures
    _safe_fail = staticmethod(safe_fail)
    _safe_set_result = staticmethod(safe_set_result)
