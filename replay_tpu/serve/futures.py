"""Shared future-resolution helpers for the serve stack.

One request = one :class:`concurrent.futures.Future`, resolved exactly once —
but resolvers race: the dispatch worker against a client-side ``cancel()``,
the fleet's hedge twins against each other, close() against an in-flight
batch. These helpers make every resolution attempt idempotent and
loss-free: they return whether THIS caller won the resolution, and losing
(the future was already done, or a racer beat us between the ``done()``
check and the commit) is never an exception. Used by both
:class:`~replay_tpu.serve.ScoringService` and
:class:`~replay_tpu.serve.ServingFleet`.
"""

from __future__ import annotations

from concurrent.futures import Future, InvalidStateError

__all__ = ["mark_running", "safe_fail", "safe_set_result"]


def safe_fail(future: "Future", exc: BaseException) -> bool:
    """Fail ``future`` with ``exc`` unless already resolved; True when this
    call did the failing."""
    try:
        if not future.done():
            future.set_exception(exc)
            return True
    except InvalidStateError:
        pass
    return False


def safe_set_result(future: "Future", result) -> bool:
    """Resolve ``future`` with ``result`` unless already resolved; True when
    this call did the resolving."""
    try:
        if not future.done():
            future.set_result(result)
            return True
    except InvalidStateError:
        pass
    return False


def mark_running(future: "Future") -> bool:
    """Commit ``future`` to RUNNING (a late ``cancel()`` no longer bites);
    False when it was cancelled — or already finished by a racer (a finished
    future raises bare ``RuntimeError`` here, NOT ``InvalidStateError``)."""
    try:
        return future.set_running_or_notify_cancel()
    except RuntimeError:
        return False
