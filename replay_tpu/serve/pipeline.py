"""Fused candidate→rank: exact MIPS retrieval + on-device re-rank + top-k.

The production path of ROADMAP item 2: instead of materializing a
``[B, |catalog|]`` score matrix per micro-batch, the encoder's last-hidden
states query the (optionally mesh-sharded) exact MIPS index
(``models/ann.py``) for the top-C candidates, a re-rank program applies the
two-stage scenario's trained logistic weights (``scenarios/two_stages.py`` —
the SAME ``LogisticReranker.decision_function`` math, run with ``jnp``), and
the final top-k cut happens on device. All three stages stay device-resident
between each other (``MIPSIndex.search_jax`` returns device arrays), so per
micro-batch the host sees only the final ``[B, k]`` ids/scores.

With the dot-product :class:`~replay_tpu.nn.head.EmbeddingTyingHead` (no
bias), MIPS scores over the item-embedding table are bitwise-identical gathers
of the full-catalog logits — retrieval loses nothing, it only skips scoring
items that cannot reach the top-C (tests pin this).

Precision-ladder rung (docs/performance.md "The precision ladder"): an
``int8``-quantized index (``MIPSIndex(..., precision="int8")``, backed by
``replay_tpu.serve.quant``) changes only the candidate SELECTION sweep — the
pipeline inserts an ``exact_rescore`` stage that re-scores the retrieved
top-C rows at full f32 precision before the re-rank/top-k cut, so the final
scores and ranking quality match the f32 pipeline whenever the quantized
sweep surfaces the same candidates (recall@C ≥ 0.99 gated in
``tests/serve/test_quant.py``), while the retrieval-dominating table bytes
drop 4×.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from replay_tpu.models.ann import MIPSIndex


class CandidatePipeline:
    """MIPS top-C retrieval → logistic re-rank → top-k, fused per micro-batch.

    :param index: the catalog's :class:`MIPSIndex` — for SASRec-style
        weight-tying models, built over ``model.get_item_weights()`` so
        retrieval scores ARE the model's logits.
    :param num_candidates: C, the retrieval cut feeding the re-ranker.
    :param top_k: k, the response cut (``k <= C``).
    :param reranker_weights: optional ``[2]`` array (retrieval-score weight,
        bias — ``LogisticReranker.serving_weights`` trained on one score
        feature). ``None`` ranks by raw retrieval score.
    """

    def __init__(
        self,
        index: MIPSIndex,
        num_candidates: int = 100,
        top_k: int = 10,
        reranker_weights: Optional[np.ndarray] = None,
    ) -> None:
        if num_candidates > index.num_items:
            msg = (
                f"num_candidates={num_candidates} exceeds the catalog "
                f"({index.num_items} items)"
            )
            raise ValueError(msg)
        if top_k > num_candidates:
            msg = f"top_k={top_k} exceeds num_candidates={num_candidates}"
            raise ValueError(msg)
        self.index = index
        self.num_candidates = int(num_candidates)
        self.top_k = int(top_k)
        self.reranker_weights = (
            np.asarray(reranker_weights, np.float32)
            if reranker_weights is not None
            else None
        )
        if self.reranker_weights is not None and self.reranker_weights.shape != (2,):
            msg = (
                "serve re-rank uses ONE feature (the retrieval score): "
                f"weights must be [2] (weight, bias), got {self.reranker_weights.shape}"
            )
            raise ValueError(msg)
        self._rerank = self._build_rerank()

    def _build_rerank(self):
        weights = (
            jnp.asarray(self.reranker_weights)
            if self.reranker_weights is not None
            else None
        )

        @partial(jax.jit, static_argnums=())
        def rerank(values: jnp.ndarray, ids: jnp.ndarray):
            # LogisticReranker.decision_function with jnp: f @ w[:-1] + w[-1]
            # over the single retrieval-score feature; sigmoid is monotone but
            # applied anyway so response scores equal host predict_proba
            if weights is None:
                ranking = values
            else:
                ranking = jax.nn.sigmoid(values * weights[0] + weights[1])
            top_scores, positions = jax.lax.top_k(ranking, self.top_k)
            return top_scores, jnp.take_along_axis(ids, positions, axis=1)

        return rerank

    def rank(self, hidden, tracer=None, span_args=None) -> Tuple[np.ndarray, np.ndarray]:
        """``[B, E]`` query states → (scores ``[B, k]``, item ids ``[B, k]``).

        The device stages are traced as ``retrieve`` / ``rescore`` /
        ``rerank`` spans when a tracer is supplied (``rescore`` only for a
        quantized index: exact f32 scores of the retrieved candidates replace
        the quantized sweep's approximate values before the re-rank cut);
        ``span_args`` merges extra args into each span — the service passes
        the batch's distributed ``trace_ids`` here so retrieval time lands on
        every traced co-rider's request timeline."""
        import contextlib

        span = tracer.span if tracer is not None else (lambda *_a, **_k: contextlib.nullcontext())
        extra = span_args or {}
        with span("retrieve", rows=int(np.shape(hidden)[0]), k=self.num_candidates, **extra):
            values, ids = self.index.search_jax(hidden, self.num_candidates)
        if self._is_approximate():
            # full-precision re-rank input: the approximate sweep (quantized
            # table and/or IVF probing) only chose WHICH C rows to score;
            # their ranking scores are exact f32
            with span("rescore", rows=int(np.shape(hidden)[0]), k=self.num_candidates, **extra):
                values = self.index.exact_rescore(hidden, ids)
        with span("rerank", rows=int(np.shape(hidden)[0]), k=self.top_k, **extra):
            scores, items = self._rerank(values, ids)
            scores = np.asarray(scores)
            items = np.asarray(items)
        return scores, items

    def _is_approximate(self) -> bool:
        # IVF probing approximates the candidate SET even at f32 scores;
        # legacy index objects without the property fall back to the
        # precision cue (only the brute f32 sweep is exact)
        return bool(
            getattr(
                self.index,
                "is_approximate",
                getattr(self.index, "precision", "f32") != "f32",
            )
        )

    def stats(self) -> Dict[str, int]:
        return {
            "num_candidates": self.num_candidates,
            "top_k": self.top_k,
            "index_precision": getattr(self.index, "precision", "f32"),
            "index_mode": getattr(self.index, "index_mode", "brute"),
        }
