"""Zero-downtime weight swaps and SLO-guarded canary promotion.

The closing of ROADMAP item 3's loop: a continually-trained candidate gets
into production *through* the live :class:`~replay_tpu.serve.ScoringService`,
never around it. Three pieces:

* :class:`ParamStore` — an atomic, versioned store of parameter
  **generations**. Params travel as program *arguments* (the PR-6
  serialization fix), so installing a new generation into the running
  per-bucket ``CompiledInference`` executables is a pointer swap, not a
  recompile: every dispatched micro-batch resolves ONE generation up front
  and runs encoder, scorer and retrieval pipeline against it — in-flight
  batches finish on the generation they started, and no response ever mixes
  an old encoder with a new scorer (no torn reads). Only a catalog-shape
  change (vocab surgery grew the item table) forces new executables, and
  those compile on the *publisher's* thread while serving continues on the
  old generation.
* :func:`in_canary_slice` — the deterministic hash-based traffic slice: a
  user is in the canary or not as a pure function of ``(user_id, fraction)``,
  so the slice is stable across requests, restarts and processes (no sticky
  session state to lose).
* :class:`PromotionController` — the guarded state machine::

      idle ──publish──▶ shadow ──begin_canary──▶ canary ──K clean evals──▶ promoted
                                                   │
                                                   └─SLO breach─▶ rolled_back

  Each :meth:`PromotionController.evaluate` folds the service's per-role
  counters into ``replay_canary_*`` gauges, runs its
  :class:`~replay_tpu.obs.slo.SLOWatchdog` over them, and acts on the
  verdict: a breach rolls back to the pinned previous generation exactly
  once (the stage transition is the latch), ``promote_after`` consecutive
  clean evaluations — each carrying at least ``min_canary_requests`` of real
  canary traffic — promote. After a rollback the candidate is burned:
  re-entering canary requires publishing a NEW generation. The clock is
  injectable for deterministic tests.

Events (``on_publish`` / ``on_swap`` / ``on_canary_start`` /
``on_canary_eval`` / ``on_promotion`` / ``on_rollback``) ride the service's
normal sink fan-out, so ``events.jsonl``, the metrics registry and
``obs.report``'s "promotion" section all see the same record. See
docs/robustness.md "Zero-downtime swaps and canary promotion".

Quality-gated canaries (obs.quality): when the service carries a
:class:`~replay_tpu.obs.QualityMonitor`, its candidate-slice gauges
(``replay_quality_*{role="candidate"}``) land in the SAME registry this
controller's watchdog reads — so passing
:func:`~replay_tpu.obs.canary_quality_rules` (or hand-written
:class:`~replay_tpu.obs.SLORule`\\ s over those labeled series) as ``rules=``
makes a canary that serves fast-but-WORSE recommendations roll back exactly
like an erroring one, with zero controller changes. The ``on_canary_eval``
record then also carries the candidate's online quality window (``quality``
key) as the decision's evidence trail.
"""

from __future__ import annotations

import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Hashable, List, Optional, Sequence

__all__ = [
    "ParamGeneration",
    "ParamStore",
    "PromotionController",
    "PROMOTION_STAGES",
    "in_canary_slice",
]

# traffic-routing roles: "stable" serves the promoted generation, "candidate"
# the canary one (falling back to stable when no candidate is published)
ROLES = ("stable", "candidate")

PROMOTION_STAGES = ("idle", "shadow", "canary", "promoted", "rolled_back")

# numeric encoding of the stage for the replay_canary_stage gauge
STAGE_GAUGE = {
    "idle": 0.0,
    "shadow": 1.0,
    "canary": 2.0,
    "promoted": 3.0,
    "rolled_back": -1.0,
}


def in_canary_slice(user_id: Hashable, fraction: float) -> bool:
    """Deterministic hash slice: is ``user_id`` in the canary ``fraction``?

    Pure function of the id and the fraction (CRC-32 over ``str(user_id)``,
    bucketed mod 10_000) — the same user always lands on the same side, on
    every process, with no session state. ``fraction`` is clamped to [0, 1].
    """
    if fraction <= 0.0:
        return False
    if fraction >= 1.0:
        return True
    bucket = zlib.crc32(str(user_id).encode()) % 10_000
    return bucket < int(fraction * 10_000)


@dataclass(frozen=True)
class ParamGeneration:
    """One immutable published parameter set.

    ``engine`` is ``None`` for same-shape generations — they run through the
    service's base executables with these params passed as the program
    argument (zero recompile). A generation whose catalog shape changed
    carries its own pre-compiled :class:`~replay_tpu.serve.ScoringEngine`
    (``recompiled=True``). ``pipeline`` is the generation's retrieval
    :class:`~replay_tpu.serve.CandidatePipeline` (its MIPS index embeds the
    item table, so it is per-generation by construction).
    """

    number: int
    params: Any
    label: str = ""
    engine: Optional[Any] = None
    pipeline: Optional[Any] = None
    recompiled: bool = False
    published_at: float = 0.0


class ParamStore:
    """Thread-safe versioned parameter store with atomic role resolution.

    One lock guards every pointer move; readers get the immutable
    :class:`ParamGeneration` object, so a swap can never be observed
    half-applied. The *previous* stable generation stays pinned after every
    promote — the rollback target — and old unpinned generations beyond
    ``keep_history`` are dropped (their metadata survives in :meth:`history`).
    """

    def __init__(
        self,
        params: Any,
        label: str = "initial",
        pipeline: Optional[Any] = None,
        keep_history: int = 3,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._lock = threading.Lock()
        self._clock = clock
        self.keep_history = int(keep_history)
        self._generations: Dict[int, ParamGeneration] = {}
        self._log: List[Dict[str, Any]] = []
        self._next = 0
        self._stable = self._publish_locked(params, label=label, pipeline=pipeline)
        self._candidate: Optional[int] = None
        self._previous: Optional[int] = None
        self.swaps = 0
        self.rollbacks = 0

    # -- publishing --------------------------------------------------------- #
    def _publish_locked(
        self,
        params: Any,
        label: str = "",
        pipeline: Optional[Any] = None,
        engine: Optional[Any] = None,
        recompiled: bool = False,
    ) -> int:
        number = self._next
        self._next += 1
        generation = ParamGeneration(
            number=number,
            params=params,
            label=label,
            engine=engine,
            pipeline=pipeline,
            recompiled=recompiled,
            published_at=self._clock(),
        )
        self._generations[number] = generation
        self._log.append(
            {
                "generation": number,
                "label": label,
                "recompiled": bool(recompiled),
                "published_at": generation.published_at,
                "event": "published",
            }
        )
        return number

    def publish(
        self,
        params: Any,
        label: str = "",
        pipeline: Optional[Any] = None,
        engine: Optional[Any] = None,
        recompiled: bool = False,
    ) -> int:
        """Register a new generation and make it the current candidate."""
        with self._lock:
            number = self._publish_locked(
                params, label=label, pipeline=pipeline, engine=engine,
                recompiled=recompiled,
            )
            self._candidate = number
            self._evict_locked()
            return number

    # -- resolution (hot path) ---------------------------------------------- #
    def resolve(self, role: str = "stable") -> ParamGeneration:
        """The generation currently serving ``role`` — atomically.

        ``"candidate"`` falls back to stable when no candidate is published
        (a canary request racing a just-finished promote must still be
        answered, by the generation that won)."""
        with self._lock:
            number = self._stable
            if role == "candidate" and self._candidate is not None:
                number = self._candidate
            return self._generations[number]

    def generation(self, number: int) -> ParamGeneration:
        with self._lock:
            if number not in self._generations:
                msg = f"generation {number} is no longer resident (evicted history)"
                raise KeyError(msg)
            return self._generations[number]

    # -- pointer moves ------------------------------------------------------ #
    def promote(self, number: Optional[int] = None) -> Dict[str, Any]:
        """Atomically make ``number`` (default: the candidate) the stable
        generation; the outgoing stable is pinned as the rollback target."""
        with self._lock:
            if number is None:
                number = self._candidate
            if number is None:
                msg = "no candidate generation to promote"
                raise ValueError(msg)
            if number not in self._generations:
                msg = f"generation {number} is not resident in the store"
                raise KeyError(msg)
            previous = self._stable
            self._previous = previous
            self._stable = number
            self._candidate = None
            self.swaps += 1
            self._log.append(
                {
                    "generation": number,
                    "from_generation": previous,
                    "at": self._clock(),
                    "event": "promoted",
                }
            )
            self._evict_locked()
            return {"from_generation": previous, "to_generation": number}

    def rollback(self) -> Dict[str, Any]:
        """Atomically undo the current candidate or the last promote.

        Mid-canary (a candidate is live but stable never moved) the rollback
        DROPS the candidate — the traffic slice snaps back to stable. After a
        promote, the pinned previous generation is restored. Raises when
        there is neither a candidate nor a pinned previous generation."""
        with self._lock:
            if self._candidate is not None:
                # canary rollback: stable never moved, burning the candidate
                # IS the restoration
                abandoned = self._candidate
                self._candidate = None
            elif self._previous is not None:
                abandoned = self._stable
                self._stable = self._previous
                self._previous = None
                self.swaps += 1
            else:
                msg = "no candidate or previous generation; nothing to roll back to"
                raise ValueError(msg)
            self.rollbacks += 1
            self._log.append(
                {
                    "generation": self._stable,
                    "from_generation": abandoned,
                    "at": self._clock(),
                    "event": "rolled_back",
                }
            )
            self._evict_locked()
            return {"from_generation": abandoned, "to_generation": self._stable}

    def clear_candidate(self) -> None:
        with self._lock:
            self._candidate = None
            self._evict_locked()

    def _evict_locked(self) -> None:
        pinned = {self._stable, self._candidate, self._previous} - {None}
        numbers = sorted(self._generations)
        # keep every pinned generation plus the most recent keep_history
        keep = pinned | set(numbers[-self.keep_history :])
        for number in numbers:
            if number not in keep:
                del self._generations[number]

    # -- introspection ------------------------------------------------------ #
    @property
    def stable_generation(self) -> int:
        with self._lock:
            return self._stable

    @property
    def candidate_generation(self) -> Optional[int]:
        with self._lock:
            return self._candidate

    @property
    def previous_generation(self) -> Optional[int]:
        with self._lock:
            return self._previous

    def history(self) -> List[Dict[str, Any]]:
        """The append-only publish/promote/rollback log (pure JSON — the
        generation-history artifact the canary_smoke CI job uploads)."""
        with self._lock:
            return [dict(entry) for entry in self._log]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "stable_generation": self._stable,
                "candidate_generation": self._candidate,
                "previous_generation": self._previous,
                "resident_generations": sorted(self._generations),
                "published": self._next,
                "swaps": self.swaps,
                "rollbacks": self.rollbacks,
            }


class PromotionController:
    """The guarded promotion state machine over a live
    :class:`~replay_tpu.serve.ScoringService`.

    :param service: the serving process; the controller publishes through
        ``service.publish_candidate`` and swaps through ``service.promote`` /
        ``service.rollback`` so every move is atomic w.r.t. dispatch.
    :param rules: :class:`~replay_tpu.obs.SLORule` set over the
        ``replay_canary_*`` gauges this controller maintains — or over any
        other series in the service's registry, e.g. the candidate-labeled
        ``replay_quality_*`` gauges a :class:`~replay_tpu.obs.QualityMonitor`
        maintains (:func:`~replay_tpu.obs.canary_quality_rules` builds that
        set). Default: any canary error rolls back
        (``replay_canary_error_rate > 0``).
    :param promote_after: consecutive clean evaluations (each with enough
        traffic) before the candidate is promoted.
    :param min_canary_requests: canary responses an evaluation window must
        carry to count as evidence — a window the slice sent no traffic
        through is neither clean nor breaching.
    :param fraction: default deterministic traffic slice for
        :meth:`begin_canary`.
    :param clock: injectable time source (tests drive the state machine
        without sleeping).
    """

    def __init__(
        self,
        service: Any,
        rules: Optional[Sequence[Any]] = None,
        promote_after: int = 3,
        min_canary_requests: int = 1,
        fraction: float = 0.1,
        clock: Callable[[], float] = time.monotonic,
        registry: Optional[Any] = None,
    ) -> None:
        from replay_tpu.obs.metrics import MetricsRegistry
        from replay_tpu.obs.slo import SLORule

        if promote_after < 1:
            msg = "promote_after must be >= 1 (clean evaluations before promote)"
            raise ValueError(msg)
        self.service = service
        self.registry = (
            registry
            if registry is not None
            else (service.metrics_registry or MetricsRegistry())
        )
        self.rules = (
            tuple(rules)
            if rules is not None
            else (
                SLORule(
                    "replay_canary_error_rate", ">", 0.0, name="canary_error_rate"
                ),
            )
        )
        self.promote_after = int(promote_after)
        self.min_canary_requests = int(min_canary_requests)
        self.fraction = float(fraction)
        self.clock = clock
        self.stage = "idle"
        self.generation: Optional[int] = None
        self.clean_evals = 0
        self.evals = 0
        self.promotions = 0
        self.rollbacks = 0
        self.watchdog = self._fresh_watchdog()
        self._last_counts: Dict[str, float] = {}

    def _fresh_watchdog(self):
        from replay_tpu.obs.slo import SLOWatchdog

        # per-canary watchdog: a previous canary's still-active breach must
        # not leak a rollback into the next candidate's first evaluation
        return SLOWatchdog(
            self.rules, self.registry, emit=self.service._route_event,
            clock=self.clock,
        )

    def _emit(self, event: str, payload: Dict[str, Any]) -> None:
        self.service._emit(event, payload)

    def _set_stage(self, stage: str) -> None:
        self.stage = stage
        self.registry.set("replay_canary_stage", STAGE_GAUGE[stage])

    # -- state machine ------------------------------------------------------ #
    def publish(
        self, params: Any, label: str = "", pipeline: Optional[Any] = None
    ) -> int:
        """Register a candidate → **shadow** stage: the generation is resident
        and addressable (``service.submit(..., _role="candidate")`` probes it)
        but serves no user traffic. Refused while a canary is LIVE — the
        running canary must be promoted or rolled back first (a silent
        candidate replacement would redirect its traffic slice to an
        unvetted generation)."""
        if self.stage == "canary":
            msg = (
                "publish during an active canary: promote or roll back the "
                "running candidate before publishing a new generation"
            )
            raise RuntimeError(msg)
        self.generation = self.service.publish_candidate(
            params, label=label, pipeline=pipeline
        )
        self.clean_evals = 0
        self.evals = 0
        self._set_stage("shadow")
        return self.generation

    def begin_canary(self, fraction: Optional[float] = None) -> None:
        """Shadow → **canary**: the deterministic slice starts serving from
        the candidate. Requires a freshly published (shadow) generation — in
        particular, re-entering canary after a rollback needs a NEW
        :meth:`publish` (the burned candidate stays burned)."""
        if self.stage != "shadow":
            msg = (
                f"begin_canary from stage {self.stage!r}: a canary needs a "
                "freshly published candidate (after a rollback, publish a new "
                "generation — the rolled-back one stays burned)"
            )
            raise RuntimeError(msg)
        fraction = self.fraction if fraction is None else float(fraction)
        self.watchdog = self._fresh_watchdog()
        self.clean_evals = 0
        self.evals = 0
        self._last_counts = {}
        self.service.begin_canary(self.generation, fraction)
        self._set_stage("canary")
        self.registry.set("replay_canary_generation", float(self.generation))

    def evaluate(self, step: Optional[int] = None) -> Dict[str, Any]:
        """One guard evaluation: fold canary counters into gauges, run the
        watchdog, act. Returns the decision record (also emitted as
        ``on_canary_eval``)."""
        if self.stage != "canary":
            return {"stage": self.stage, "action": None}
        stats = self.service.canary_stats()["candidate"]
        window = {
            key: stats.get(key, 0.0) - self._last_counts.get(key, 0.0)
            for key in ("requests", "answered", "errors", "shed")
        }
        self._last_counts = {
            key: stats.get(key, 0.0)
            for key in ("requests", "answered", "errors", "shed")
        }
        seen = window["answered"] + window["errors"]
        error_rate = window["errors"] / seen if seen else 0.0
        self.evals += 1
        self.registry.set("replay_canary_requests", float(window["requests"]))
        self.registry.set("replay_canary_error_rate", float(error_rate))
        self.registry.set(
            "replay_canary_queue_wait_ms_max", float(stats.get("queue_wait_ms_max", 0.0))
        )
        self.registry.set("replay_canary_generation", float(self.generation))
        self.watchdog.evaluate(step)
        action: Optional[str] = None
        breached = list(self.watchdog.active)
        if breached:
            action = "rollback"
        elif seen >= self.min_canary_requests:
            self.clean_evals += 1
            if self.clean_evals >= self.promote_after:
                action = "promote"
        self.registry.set("replay_canary_clean_evals", float(self.clean_evals))
        record = {
            "stage": self.stage,
            "generation": self.generation,
            "action": action,
            "window": window,
            "error_rate": error_rate,
            "clean_evals": self.clean_evals,
            "evals": self.evals,
            "breached_rules": breached,
        }
        monitor = getattr(self.service, "quality", None)
        if monitor is not None:
            # the decision's quality evidence: the candidate slice's online
            # window at evaluation time (what the quality rules just judged)
            candidate = (monitor.snapshot().get("roles") or {}).get("candidate")
            if candidate:
                record["quality"] = {
                    key: candidate.get(key)
                    for key in (
                        "joins", "online_hitrate_cum", "online_ndcg_cum",
                        "coverage", "novelty", "popularity",
                    )
                    if candidate.get(key) is not None
                }
        self._emit("on_canary_eval", dict(record))
        if action == "rollback":
            self._rollback(breached)
        elif action == "promote":
            self._promote()
        return record

    def _rollback(self, breached: List[str]) -> None:
        info = self.service.rollback()
        self.rollbacks += 1
        self._set_stage("rolled_back")
        self._emit(
            "on_rollback",
            {
                "generation": self.generation,
                "restored_generation": info["to_generation"],
                "rules": breached,
                "evals": self.evals,
            },
        )

    def _promote(self) -> None:
        info = self.service.promote(self.generation)
        self.promotions += 1
        self._set_stage("promoted")
        self._emit(
            "on_promotion",
            {
                "generation": self.generation,
                "from_generation": info["from_generation"],
                "clean_evals": self.clean_evals,
                "evals": self.evals,
            },
        )

    def stats(self) -> Dict[str, Any]:
        return {
            "stage": self.stage,
            "generation": self.generation,
            "clean_evals": self.clean_evals,
            "evals": self.evals,
            "promotions": self.promotions,
            "rollbacks": self.rollbacks,
            "rules": [getattr(rule, "label", str(rule)) for rule in self.rules],
        }
