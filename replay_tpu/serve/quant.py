"""Post-training int8 quantization for serving retrieval — the ladder's rung 2.

The bytes that dominate `CandidatePipeline` retrieval latency are the item
embedding table sweep: exact MIPS reads all ``[I, E]`` f32 rows per
micro-batch, and at 10M items × d=128 that is 5 GB — past a single device's
HBM before the model itself is counted (sub-item-IDs paper's memory-per-item
budget, PAPERS.md). Per-item symmetric int8 quantization cuts the sweep 4×:

* **per-row symmetric scales** — ``scale_i = absmax(row_i) / 127``,
  ``q_i = round(row_i / scale_i)`` as int8. No zero points (symmetric), so
  the dequantized score is ``(queries @ q.T) * scale`` — one multiply per
  score, fused by XLA into the matmul epilogue. Weight-only quantization: the
  int8 rows are up-cast in registers after the (¼-sized) HBM read; queries
  stay full precision.
* **re-rank at full precision** — quantized scores pick the top-C CANDIDATES;
  the pipeline then rescores exactly those C rows against the f32 master
  copy (``MIPSIndex.exact_rescore``) before the re-rank/top-k cut, so
  end-to-end top-k quality is preserved (recall@C ≥ 0.99 is the tested gate,
  ``tests/serve/test_quant.py``) while HBM holds only int8 rows.
* **sharded layout reuse** — a mesh-sharded quantized index keeps the
  CEFusedTP ``[I/n, E]`` row-shard layout (int8 values ``P(axis, None)``,
  scales ``P(axis)``), which is what lets 10M-item tables fit where f32
  cannot (ROADMAP items 4+5).

Training NEVER sees int8 — the :class:`~replay_tpu.nn.loss.CEFused` dtype
check rejects integer tables by name. Quantization here is post-training and
serving-only.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import numpy as np

__all__ = [
    "QuantizedTable",
    "quantization_error",
    "quantize_embeddings",
]


@dataclass(frozen=True)
class QuantizedTable:
    """Per-row symmetrically quantized embedding table.

    ``values`` is the int8 payload ``[I, E]``; ``scales`` the f32 per-row
    dequantization factors ``[I]`` (``row_i ≈ values_i * scales_i``). Rows
    that were entirely zero carry scale 0 and dequantize to exact zeros.
    """

    values: np.ndarray  # int8 [I, E]
    scales: np.ndarray  # f32 [I]

    @property
    def num_items(self) -> int:
        return int(self.values.shape[0])

    @property
    def dim(self) -> int:
        return int(self.values.shape[1])

    @property
    def nbytes(self) -> int:
        """Total payload bytes (int8 values + f32 scales) — the number the
        bench rows compare against the f32 table's ``I × E × 4``."""
        return int(self.values.nbytes + self.scales.nbytes)

    def dequantize(self) -> np.ndarray:
        """The f32 approximation ``values * scales[:, None]`` (error ≤
        scale/2 per element — see :func:`quantization_error`)."""
        return self.values.astype(np.float32) * self.scales[:, None]


def quantize_embeddings(table: np.ndarray, bits: int = 8) -> QuantizedTable:
    """Per-item (per-row) symmetric quantization of an ``[I, E]`` f32 table.

    Symmetric (no zero point): ``scale = absmax / qmax`` with ``qmax =
    2^(bits-1) - 1`` (127 for int8), values round-to-nearest. Per-ROW scales
    keep popular high-norm items from crushing the resolution of the long
    tail — the per-tensor alternative loses recall precisely on the rows
    retrieval cares about.
    """
    if bits != 8:
        msg = f"only int8 is supported (bits=8), got bits={bits}"
        raise ValueError(msg)
    table = np.asarray(table, np.float32)
    if table.ndim != 2:
        msg = f"expected an [num_items, embed] table, got shape {table.shape}"
        raise ValueError(msg)
    qmax = float(2 ** (bits - 1) - 1)
    absmax = np.max(np.abs(table), axis=1)  # [I]
    scales = (absmax / qmax).astype(np.float32)
    # zero rows: scale 0 would divide by zero; quantize them to zeros exactly
    safe = np.where(scales > 0.0, scales, 1.0)
    values = np.clip(np.rint(table / safe[:, None]), -qmax, qmax).astype(np.int8)
    values[scales == 0.0] = 0
    return QuantizedTable(values=values, scales=scales)


def quantization_error(table: np.ndarray, quantized: QuantizedTable) -> Dict[str, Any]:
    """Round-trip error stats: per-element absolute error is bounded by
    ``scale/2`` (round-to-nearest of a symmetric grid); the record carries the
    observed max against that bound plus the relative Frobenius error."""
    table = np.asarray(table, np.float32)
    approx = quantized.dequantize()
    abs_err = np.abs(approx - table)
    bound = quantized.scales[:, None] / 2.0
    denom = float(np.linalg.norm(table)) or 1.0
    return {
        "max_abs_error": float(abs_err.max(initial=0.0)),
        "max_error_to_bound": float(
            np.max(abs_err / np.maximum(bound, 1e-12), initial=0.0)
        ),
        "rel_frobenius_error": float(np.linalg.norm(approx - table)) / denom,
        "bytes_f32": int(table.nbytes),
        "bytes_int8": quantized.nbytes,
        "bytes_ratio": quantized.nbytes / max(int(table.nbytes), 1),
    }
