"""Socket-boundary replicas: the fleet's duck-typed surface over real HTTP.

Everything the fleet proved so far (``serve/fleet.py``: routing, failover,
hedging, backoff, drain) was exercised against in-process replica objects —
a thread boundary, not a process one. This module graduates that seam:

* :class:`ReplicaServer` gives one :class:`~replay_tpu.serve.ScoringService`
  its own HTTP front in its own OS process — ``POST /score`` (blocking
  request/response), ``GET /healthz`` (the structured heartbeat document,
  the same shape :mod:`replay_tpu.obs.exporter` serves under
  ``?format=json``) and ``GET /stats``. The serve-error taxonomy maps onto
  HTTP statuses (shed → 429, breaker/closed → 503, deadline → 504, cold
  re-anchor → 404) so WHY a request was refused — and ``retry_after_s`` —
  survives the wire.

* :class:`RemoteReplica` is the client half: the exact
  ``submit/score/heartbeat/stats/start/close`` surface
  :class:`~replay_tpu.serve.ServingFleet` duck-types over, so the PR-15
  router/failover/hedge/drain machinery runs UNCHANGED above it. Refusal
  payloads are reconstructed into the same exception types
  (:class:`~replay_tpu.serve.errors.RequestShed` with its ``retry_after_s``
  intact, etc.); transport failures — connection refused, reset, timeout:
  what a SIGKILLed server process actually produces — surface as
  :class:`~replay_tpu.serve.errors.ServiceClosed`, the retryable refusal
  that sends the router shopping downstream while heartbeat misses declare
  the replica dead. ``heartbeat()`` is a pure remote scrape of
  ``/healthz?format=json``: the monitor drives ``ReplicaHealth`` from the
  live bit, lane depth, breaker state and windowed error-rate gauges of a
  process it shares no memory with.

* :class:`ReplicaServerProcess` spawns ``python -m replay_tpu.serve.remote``
  (a small demo SasRec service by default) and handshakes the ephemeral
  port through a portfile — the server binds port 0 and PUBLISHES the bound
  address; nothing is hardcoded, so N servers and N test sessions coexist
  on one host. ``respawn()`` restarts a SIGKILLed server on a FRESH port;
  :attr:`address` re-reads the portfile, so a :class:`RemoteReplica` built
  over the process object follows the replica across restarts.

Used by ``tests/serve/test_remote.py`` (socket fleet + SIGKILL chaos) and
``bench_fleet.py``'s socket-chaos phase (docs/robustness.md "Elastic resume
and hard-kill chaos").
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import Future
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Hashable, Optional, Sequence

import numpy as np

from .errors import (
    CircuitOpen,
    DeadlineExceeded,
    RequestShed,
    ServiceClosed,
)
from .futures import safe_fail, safe_set_result
from .request import ScoreResponse

logger = logging.getLogger("replay_tpu")

__all__ = ["RemoteReplica", "ReplicaServer", "ReplicaServerProcess"]


# -- taxonomy <-> HTTP ------------------------------------------------------- #
def _error_payload(exc: BaseException) -> tuple:
    """(status, payload) for one refusal: enough fields ride the wire that
    the client reconstructs the SAME exception, hints intact."""
    if isinstance(exc, RequestShed):
        return 429, {
            "error": "RequestShed",
            "lane": str(exc.lane),
            "depth": exc.depth,
            "max_depth": exc.max_depth,
            "retry_after_s": exc.retry_after_s,
        }
    if isinstance(exc, CircuitOpen):
        return 503, {"error": "CircuitOpen", "retry_after_s": exc.retry_after_s}
    if isinstance(exc, ServiceClosed):
        return 503, {"error": "ServiceClosed", "detail": str(exc)}
    if isinstance(exc, DeadlineExceeded):
        return 504, {
            "error": "DeadlineExceeded",
            "waited_s": exc.waited_s,
            "deadline_s": exc.deadline_s,
        }
    if isinstance(exc, KeyError):
        # the cold-reanchor contract: an interaction that cannot land on a
        # cold cache refuses loudly — a distinct status, not a 500
        return 404, {"error": "KeyError", "detail": str(exc.args[0]) if exc.args else ""}
    return 500, {"error": type(exc).__name__, "detail": repr(exc)}


def _rebuild_error(status: int, payload: Dict[str, Any]) -> BaseException:
    kind = payload.get("error")
    if kind == "RequestShed":
        return RequestShed(
            payload.get("lane"),
            int(payload.get("depth") or 0),
            int(payload.get("max_depth") or 0),
            retry_after_s=payload.get("retry_after_s"),
        )
    if kind == "CircuitOpen":
        return CircuitOpen(retry_after_s=payload.get("retry_after_s"))
    if kind == "ServiceClosed":
        return ServiceClosed(payload.get("detail") or "service is not running")
    if kind == "DeadlineExceeded":
        return DeadlineExceeded(
            float(payload.get("waited_s") or 0.0),
            float(payload.get("deadline_s") or 0.0),
        )
    if kind == "KeyError":
        return KeyError(payload.get("detail") or "cold cache")
    return RuntimeError(payload.get("detail") or f"replica error (HTTP {status})")


def _response_payload(response: ScoreResponse) -> Dict[str, Any]:
    return {
        "user_id": response.user_id,
        "scores": np.asarray(response.scores).tolist(),
        "item_ids": (
            np.asarray(response.item_ids).tolist()
            if response.item_ids is not None
            else None
        ),
        "served_from": response.served_from,
        "served_by": response.served_by,
        "lane": response.lane,
        "queue_wait_s": response.queue_wait_s,
        "batch_bucket": response.batch_bucket,
        "generation": response.generation,
        "role": response.role,
    }


def _rebuild_response(payload: Dict[str, Any]) -> ScoreResponse:
    return ScoreResponse(
        user_id=payload["user_id"],
        scores=np.asarray(payload["scores"], np.float32),
        item_ids=(
            np.asarray(payload["item_ids"], np.int32)
            if payload.get("item_ids") is not None
            else None
        ),
        served_from=payload["served_from"],
        served_by=payload.get("served_by", "primary"),
        lane=payload.get("lane", ""),
        queue_wait_s=float(payload.get("queue_wait_s") or 0.0),
        batch_bucket=int(payload.get("batch_bucket") or 0),
        generation=int(payload.get("generation") or 0),
        role=payload.get("role", "stable"),
    )


# -- server ------------------------------------------------------------------ #
class _ReplicaHandler(BaseHTTPRequestHandler):
    server: "_ReplicaHTTPServer"
    protocol_version = "HTTP/1.1"  # keep-alive: one client socket, N requests

    def _respond(self, status: int, payload: Dict[str, Any]) -> None:
        body = json.dumps(payload, default=str).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler contract
        try:
            path, _, _ = self.path.partition("?")
            if path == "/healthz":
                # same document the exporter's /healthz?format=json serves:
                # a raising heartbeat answers 503, never a happy 200
                try:
                    self._respond(200, dict(self.server.service.heartbeat()))
                except Exception as exc:  # noqa: BLE001 — the signal itself
                    self._respond(503, {"live": False, "error": repr(exc)})
            elif path == "/stats":
                try:
                    self._respond(200, dict(self.server.service.stats()))
                except Exception as exc:  # noqa: BLE001
                    self._respond(500, {"error": type(exc).__name__, "detail": repr(exc)})
            else:
                self._respond(404, {"error": "not found"})
        except (BrokenPipeError, ConnectionResetError):
            pass  # client hung up mid-response

    def do_POST(self) -> None:  # noqa: N802
        try:
            path, _, _ = self.path.partition("?")
            if path != "/score":
                self._respond(404, {"error": "not found"})
                return
            length = int(self.headers.get("Content-Length") or 0)
            request = json.loads(self.rfile.read(length) or b"{}")
            try:
                future = self.server.service.submit(
                    request["user_id"],
                    history=request.get("history"),
                    new_items=tuple(request.get("new_items") or ()),
                    k=request.get("k"),
                    candidates=request.get("candidates"),
                    deadline_ms=request.get("deadline_ms"),
                    _trace=request.get("_trace"),
                )
                # block THIS handler thread (ThreadingHTTPServer: one thread
                # per connection) — the socket analog of Future.result(). The
                # wait is bounded: the service's own deadline/close paths
                # resolve every future, plus a transport-level backstop
                timeout = self.server.request_timeout_s
                deadline_ms = request.get("deadline_ms")
                if deadline_ms is not None:
                    timeout = max(float(deadline_ms) / 1000.0 + 5.0, 5.0)
                response = future.result(timeout=timeout)
            except Exception as exc:  # noqa: BLE001 — mapped, not masked
                status, payload = _error_payload(exc)
                self._respond(status, payload)
                return
            self._respond(200, _response_payload(response))
        except (BrokenPipeError, ConnectionResetError):
            pass

    def log_message(self, format: str, *args) -> None:  # noqa: A002
        pass  # request-rate log lines must not spam the replica's stderr


class _ReplicaHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    service: Any
    request_timeout_s: float


class ReplicaServer:
    """One scoring service behind a real HTTP socket.

    Binds ``port`` (default 0 → OS-chosen, published via :attr:`port` /
    :attr:`address` and optionally a ``portfile``) and serves until
    :meth:`close`. The handler threads block inside ``Future.result`` while
    the service's micro-batcher does the device work — the same no-hung-
    requests contract as in-process, now observable only through the socket.
    """

    def __init__(
        self,
        service: Any,
        port: int = 0,
        host: str = "127.0.0.1",
        request_timeout_s: float = 120.0,
        portfile: Optional[str] = None,
    ) -> None:
        self.service = service
        self.host = host
        self.portfile = portfile
        self._server = _ReplicaHTTPServer((host, int(port)), _ReplicaHandler)
        self._server.service = service
        self._server.request_timeout_s = float(request_timeout_s)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ReplicaServer":
        if self._thread is not None:
            return self
        self.service.start()
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="replica-server",
            daemon=True,
        )
        self._thread.start()
        if self.portfile:
            # atomic publish: a reader never sees a half-written port
            tmp = f"{self.portfile}.tmp"
            with open(tmp, "w") as fh:
                fh.write(self.address)
            os.replace(tmp, self.portfile)
            exporter = getattr(self.service, "metrics_exporter", None)
            if exporter is not None and exporter.url is not None:
                # the ephemeral metrics port, published the same atomic way,
                # so a federation scraper (obs.federate) can find every
                # replica's /snapshot without a fixed-port convention
                tmp = f"{self.portfile}.metrics.tmp"
                with open(tmp, "w") as fh:
                    fh.write(exporter.url)
                os.replace(tmp, f"{self.portfile}.metrics")
        logger.info("replica server on %s", self.address)
        return self

    def serve_forever(self) -> None:
        """Start and park the calling thread until SIGTERM/SIGINT (the
        ``python -m replay_tpu.serve.remote`` main loop). SIGKILL, of
        course, never reaches this — that is the point of the chaos tests."""
        stop = threading.Event()
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda *_: stop.set())
        self.start()
        stop.wait()
        self.close()

    def close(self) -> None:
        thread, self._thread = self._thread, None
        if thread is not None:
            self._server.shutdown()
            thread.join(timeout=5.0)
        self._server.server_close()
        self.service.close()


# -- client ------------------------------------------------------------------ #
class RemoteReplica:
    """The fleet-facing client for one :class:`ReplicaServer`.

    :param target: the server's base address (``http://host:port``) or any
        object with an ``.address`` attribute (a
        :class:`ReplicaServerProcess`) — resolved PER REQUEST, so a respawned
        server on a fresh port is picked up without rebuilding the fleet.
    :param max_connections: worker threads doing the blocking HTTP calls
        (the client-side analog of the service's handler threads).
    :param heartbeat_timeout_s: the /healthz scrape budget. A dead process
        answers with connection-refused inside one kernel round-trip, so the
        monitor's miss accounting stays on its own cadence.
    """

    def __init__(
        self,
        target: Any,
        max_connections: int = 8,
        request_timeout_s: float = 120.0,
        heartbeat_timeout_s: float = 2.0,
    ) -> None:
        self._target = target
        self.request_timeout_s = float(request_timeout_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self._max_connections = int(max_connections)
        self._pool: Optional[Any] = None
        self._lock = threading.Lock()

    @property
    def address(self) -> str:
        address = getattr(self._target, "address", self._target)
        return str(address).rstrip("/")

    # -- the ScoringService duck-typed surface ------------------------------ #
    def start(self) -> "RemoteReplica":
        from concurrent.futures import ThreadPoolExecutor

        with self._lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self._max_connections,
                    thread_name_prefix="remote-replica",
                )
        return self

    def close(self) -> None:
        """Client-side only: the server process's lifecycle belongs to
        whoever spawned it (:class:`ReplicaServerProcess`/the operator) —
        a fleet closing must not take down a replica other fleets share."""
        with self._lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    def submit(
        self,
        user_id: Hashable,
        history: Optional[Sequence[int]] = None,
        new_items: Sequence[int] = (),
        k: Optional[int] = None,
        candidates: Optional[Sequence[int]] = None,
        deadline_ms: Optional[float] = None,
        _role: Optional[str] = None,
        _trace: Optional[dict] = None,
    ) -> "Future[ScoreResponse]":
        """Never blocks, never hangs: the POST runs on a pool thread; every
        failure mode — taxonomy refusal, transport death, closed client —
        fails the future with a real exception."""
        future: "Future[ScoreResponse]" = Future()
        body = {
            "user_id": user_id,
            "history": list(history) if history is not None else None,
            "new_items": list(new_items),
            "k": k,
            "candidates": list(candidates) if candidates is not None else None,
            "deadline_ms": deadline_ms,
            "_trace": _trace,
        }
        with self._lock:
            pool = self._pool
        if pool is None:
            safe_fail(future, ServiceClosed("remote replica client is not running"))
            return future
        try:
            pool.submit(self._score_worker, future, body)
        except RuntimeError:  # pool shut down between the check and submit
            safe_fail(future, ServiceClosed("remote replica client is not running"))
        return future

    def _score_worker(self, future: "Future[ScoreResponse]", body: Dict[str, Any]) -> None:
        # honor a fleet-side cancel (a hedge's losing twin) before paying for
        # the HTTP round trip — the socket analog of the batch builder
        # skipping cancelled waiters
        if not future.set_running_or_notify_cancel():
            return
        timeout = self.request_timeout_s
        if body.get("deadline_ms") is not None:
            timeout = max(float(body["deadline_ms"]) / 1000.0 + 10.0, 10.0)
        try:
            status, payload = self._http_json(
                "POST", "/score", body=body, timeout=timeout
            )
        except Exception as exc:  # noqa: BLE001 — transport death
            # connection refused/reset/timeout: what a SIGKILLed server
            # actually looks like from here. ServiceClosed is the retryable
            # refusal that sends the router downstream while heartbeat
            # misses do the declaring
            safe_fail(
                future,
                ServiceClosed(f"replica at {self.address} unreachable: {exc!r}"),
            )
            return
        if status == 200:
            safe_set_result(future, _rebuild_response(payload))
        else:
            safe_fail(future, _rebuild_error(status, payload))

    def score(self, user_id, timeout: Optional[float] = 60.0, **kwargs) -> ScoreResponse:
        if timeout is not None and "deadline_ms" not in kwargs:
            kwargs["deadline_ms"] = timeout * 1000.0
        return self.submit(user_id, **kwargs).result(timeout=timeout)

    def heartbeat(self) -> Dict[str, Any]:
        """A pure remote scrape: the health document the fleet monitor feeds
        to ``ReplicaHealth`` comes off the wire, not out of shared memory.
        Raises on ANY transport failure — the monitor counts the miss."""
        status, payload = self._http_json(
            "GET", "/healthz?format=json", timeout=self.heartbeat_timeout_s
        )
        if status != 200:
            # a 503 heartbeat ({"live": false, ...}) is still a document:
            # the monitor reads live=False and counts the miss itself
            return payload if isinstance(payload, dict) else {"live": False}
        return payload

    def stats(self) -> Dict[str, Any]:
        status, payload = self._http_json("GET", "/stats", timeout=self.request_timeout_s)
        if status != 200:
            raise RuntimeError(f"replica /stats answered {status}: {payload}")
        return payload

    # -- transport ----------------------------------------------------------- #
    def _http_json(
        self,
        method: str,
        path: str,
        body: Optional[Dict[str, Any]] = None,
        timeout: float = 30.0,
    ) -> tuple:
        data = json.dumps(body).encode() if body is not None else None
        request = urllib.request.Request(
            f"{self.address}{path}",
            data=data,
            method=method,
            headers={"Content-Type": "application/json"} if data else {},
        )
        try:
            with urllib.request.urlopen(request, timeout=timeout) as reply:
                return reply.status, json.loads(reply.read() or b"{}")
        except urllib.error.HTTPError as exc:
            # a taxonomy status with a JSON body is an ANSWER, not transport
            # death — read it through
            payload = exc.read()
            try:
                return exc.code, json.loads(payload or b"{}")
            except ValueError:
                return exc.code, {"error": "http", "detail": payload.decode(errors="replace")}


# -- process spawning -------------------------------------------------------- #
class ReplicaServerProcess:
    """Spawn ``python -m replay_tpu.serve.remote`` as a real OS process and
    handshake its ephemeral port through a portfile.

    The argv/env carry NO port: the server binds 0 and publishes. ``env``
    should come from :func:`replay_tpu.parallel.launch.clean_cpu_env` in
    tests (the TPU-relay sitecustomize must never serialize N replica
    startups on the device grant).
    """

    def __init__(
        self,
        env: Optional[Dict[str, str]] = None,
        args: Sequence[str] = (),
        python: str = sys.executable,
        startup_timeout_s: float = 120.0,
        flight_path: Optional[str] = None,
        metrics_port: Optional[int] = None,
    ) -> None:
        self._env = dict(env) if env is not None else dict(os.environ)
        self._args = [str(a) for a in args]
        if flight_path is not None:
            self._args += ["--flight-path", str(flight_path)]
        if metrics_port is not None:
            self._args += ["--metrics-port", str(metrics_port)]
        self.flight_path = flight_path
        self._python = python
        self._startup_timeout_s = float(startup_timeout_s)
        self._dir = tempfile.mkdtemp(prefix="replica_server_")
        self.portfile = os.path.join(self._dir, "port")
        self.proc: Optional[subprocess.Popen] = None
        self._spool = None

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    @property
    def address(self) -> str:
        with open(self.portfile) as fh:
            return fh.read().strip()

    @property
    def metrics_url(self) -> Optional[str]:
        """The replica's published metrics exporter URL (``--metrics-port``),
        or ``None`` before the server wrote ``<portfile>.metrics``."""
        try:
            with open(f"{self.portfile}.metrics") as fh:
                return fh.read().strip()
        except OSError:
            return None

    def spawn(self, wait: bool = True) -> "ReplicaServerProcess":
        """Start the server process. ``wait=False`` returns immediately so N
        replicas can compile their engines concurrently; follow with
        :meth:`wait_ready` before using :attr:`address`."""
        if self.proc is not None and self.proc.poll() is None:
            return self
        if os.path.exists(self.portfile):
            os.unlink(self.portfile)  # a respawn must publish a FRESH port
        self._spool = tempfile.TemporaryFile()
        self.proc = subprocess.Popen(
            [
                self._python,
                "-m",
                "replay_tpu.serve.remote",
                "--portfile",
                self.portfile,
                *self._args,
            ],
            env=self._env,
            stdout=self._spool,
            stderr=self._spool,
        )
        return self.wait_ready() if wait else self

    def wait_ready(self) -> "ReplicaServerProcess":
        deadline = time.monotonic() + self._startup_timeout_s
        while time.monotonic() < deadline:
            if os.path.exists(self.portfile):
                return self
            if self.proc is None or self.proc.poll() is not None:
                rc = self.proc.returncode if self.proc is not None else None
                raise RuntimeError(
                    f"replica server died during startup (rc={rc}):\n"
                    f"{self.output()[-2000:]}"
                )
            time.sleep(0.05)
        self.terminate()
        raise RuntimeError(
            f"replica server did not publish a port within "
            f"{self._startup_timeout_s:.0f}s:\n{self.output()[-2000:]}"
        )

    def respawn(self) -> "ReplicaServerProcess":
        """Bring a (SIGKILLed) server back — on a fresh ephemeral port; a
        :class:`RemoteReplica` holding this object follows automatically."""
        return self.spawn()

    def output(self) -> str:
        if self._spool is None:
            return ""
        self._spool.seek(0)
        return self._spool.read().decode(errors="replace")

    def terminate(self, timeout_s: float = 10.0) -> Optional[int]:
        if self.proc is None:
            return None
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=timeout_s)
        return self.proc.returncode

    def __enter__(self) -> "ReplicaServerProcess":
        return self.spawn()

    def __exit__(self, *exc_info) -> None:
        self.terminate()


# -- demo server main -------------------------------------------------------- #
def _build_demo_service(
    num_items: int,
    seq_len: int,
    embedding_dim: int,
    num_blocks: int,
    cache_capacity: int,
    max_wait_ms: float,
    flight_path: Optional[str] = None,
    metrics_port: Optional[int] = None,
):
    """The tiny deterministic SasRec service every demo replica runs: seed 0
    everywhere, so N independently-spawned servers hold IDENTICAL params and
    the fleet's parity/locality claims carry over the socket."""
    import jax

    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn.sequential.sasrec import SasRec
    from replay_tpu.serve import FallbackScorer, ScoringService

    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id",
            FeatureType.CATEGORICAL,
            is_seq=True,
            feature_hint=FeatureHint.ITEM_ID,
            cardinality=num_items,
            embedding_dim=embedding_dim,
        )
    )
    model = SasRec(
        schema=schema,
        embedding_dim=embedding_dim,
        num_blocks=num_blocks,
        num_heads=1,
        max_sequence_length=seq_len,
        dropout_rate=0.0,
    )
    init_ids = np.zeros((2, seq_len), np.int32)
    params = model.init(
        jax.random.PRNGKey(0), {"item_id": init_ids}, np.ones((2, seq_len), bool)
    )["params"]
    popularity = np.random.default_rng(0).integers(0, num_items, size=2048)
    fallback = FallbackScorer.from_interactions(popularity, num_items)
    return ScoringService(
        model,
        params,
        batch_buckets=(1, 8),
        max_wait_ms=max_wait_ms,
        cache_capacity=cache_capacity,
        cold_miss="fallback",
        fallback=fallback,
        flight_path=flight_path,
        metrics_port=metrics_port,
    )


def main(argv: Optional[Sequence[str]] = None) -> None:
    import argparse

    parser = argparse.ArgumentParser(description="demo scoring replica server")
    parser.add_argument("--portfile", required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--num-items", type=int, default=64)
    parser.add_argument("--seq-len", type=int, default=12)
    parser.add_argument("--embedding-dim", type=int, default=8)
    parser.add_argument("--num-blocks", type=int, default=1)
    parser.add_argument("--cache", type=int, default=512)
    parser.add_argument("--max-wait-ms", type=float, default=2.0)
    parser.add_argument(
        "--flight-path",
        default=None,
        help="record serve events into a SIGKILL-proof flight ring here "
        "(obs.blackbox); defaults to $REPLAY_TPU_FLIGHT_PATH",
    )
    parser.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        help="serve /metrics + /snapshot on this port (0 = ephemeral, "
        "published to <portfile>.metrics for federation scrapers)",
    )
    args = parser.parse_args(argv)

    service = _build_demo_service(
        num_items=args.num_items,
        seq_len=args.seq_len,
        embedding_dim=args.embedding_dim,
        num_blocks=args.num_blocks,
        cache_capacity=args.cache,
        max_wait_ms=args.max_wait_ms,
        flight_path=args.flight_path,
        metrics_port=args.metrics_port,
    )
    ReplicaServer(service, port=args.port, portfile=args.portfile).serve_forever()


if __name__ == "__main__":
    main()
