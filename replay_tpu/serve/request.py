"""Request/response types of the scoring service.

The online analog of the reference's predict call: one request is ONE user's
"score my next item" query. Requests carry either a full interaction history
(cold start / exact-parity fallback) or just the incremental tail (``new_items``)
for users whose encoded state the service already caches — or nothing beyond
the user id, when a cached state should be scored as-is (the pure cache hit).
"""

from __future__ import annotations

from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Hashable, Optional, Sequence, Tuple

import numpy as np

# how a response was produced, in decreasing order of cache leverage
# ("fallback" is the degradation ladder's host-side floor — no device state)
SERVED_FROM = ("hit", "advance", "cold", "fallback")


@dataclass
class ScoreRequest:
    """One user's scoring query.

    :param user_id: cache key (any hashable).
    :param history: full item-id history, oldest → newest. Required for users
        the service has no cached state for; when given alongside a cached
        state it WINS and refreshes the cache (the exact-parity fallback).
    :param new_items: incremental interactions to append to the cached window
        (the one-step update path for returning users).
    :param k: top-k cut of the response. ``None`` returns full-catalog scores
        (or the compiled slate's scores); retrieval-mode services default to
        their pipeline's ``top_k``.
    :param candidates: per-request candidate item ids, scored by exact gather
        from the full-catalog scores (full mode only).
    :param deadline_ms: end-to-end latency budget. A request still queued when
        it expires is dropped at batch-build time (its future fails with
        :class:`~replay_tpu.serve.errors.DeadlineExceeded`) and never reaches
        the device. ``None`` = no deadline.
    """

    user_id: Hashable
    history: Optional[Sequence[int]] = None
    new_items: Sequence[int] = ()
    k: Optional[int] = None
    candidates: Optional[Sequence[int]] = None
    deadline_ms: Optional[float] = None


@dataclass
class ScoreResponse:
    """Scores for one request.

    ``item_ids`` is populated for ranked responses (retrieval mode and top-k
    cuts); for full-catalog scores it is ``None`` and ``scores[i]`` is item
    ``i``'s score. In BOTH representations :func:`top_k_cut` recovers the
    ranked top-k ``(item_ids, scores)`` pair — the one contract every
    downstream consumer (quality telemetry, bench clients) relies on, so a
    response never needs to know which shape it was served in. ``item_ids``
    order is NOT guaranteed sorted by score (the candidate-gather path keeps
    request order); ``top_k_cut`` always re-ranks.
    """

    user_id: Hashable
    scores: np.ndarray
    item_ids: Optional[np.ndarray]
    served_from: str  # one of SERVED_FROM
    lane: str
    queue_wait_s: float
    # the compiled batch bucket this response's micro-batch ran at. Scores are
    # bitwise independent of fill level / co-riders / row order WITHIN a
    # bucket program, so (lane, batch_bucket) pins the exact program whose
    # direct forward_inference output this response reproduces bit-for-bit.
    batch_bucket: int = 0
    # which degradation-ladder rung produced this response (see serve.degrade):
    # "primary" keeps the full bitwise parity contract; "cache_only" scored a
    # possibly-stale cached state through the hit lane; "fallback" is the
    # host-side popularity floor. Degraded traffic is always visible here.
    served_by: str = "primary"
    # the parameter generation that produced every score in this response
    # (serve.promote): encoder, scorer and retrieval pipeline all resolved
    # from ONE generation per dispatched batch — a hot swap or rollback can
    # never tear a response across generations. ``role`` is the traffic slice
    # that routed it ("stable", or "candidate" during a canary).
    generation: int = 0
    role: str = "stable"
    # which fleet replica answered (serve.fleet): stamped by the router when
    # the request rode a ServingFleet — together with ``served_by`` this is
    # the failover proof trail (a rerouted user's degraded answer names both
    # the rung AND the replica that took it). None for direct single-service
    # scoring.
    replica: Optional[str] = None
    # the distributed-trace id of the request that produced this answer,
    # stamped by the fleet router when its tracer is on — the client-side
    # handle into the merged trace.json (chaos probes record it so a slow
    # failover links straight to its timeline). None when tracing is off.
    trace_id: Optional[str] = None


@dataclass
class PendingRequest:
    """Internal: a submitted request riding the micro-batcher queue.

    The window/mask/length snapshot is resolved on the CLIENT thread at submit
    time (cheap numpy bookkeeping) so the serve worker only stacks rows and
    runs device programs; ``enqueued_at`` is tracer-epoch-relative
    (``Tracer.now()``) for the cross-thread ``queue_wait`` span.
    """

    request: ScoreRequest
    future: "Future[ScoreResponse]"
    served_from: str
    window: Optional[np.ndarray] = None  # [L_max] int32, right-aligned
    mask: Optional[np.ndarray] = None  # [L_max] bool
    length: int = 0
    embedding: Optional[np.ndarray] = None  # [E] — pure-hit lane only
    enqueued_at: float = 0.0
    extra: Tuple[Any, ...] = field(default=())
    # resilience bookkeeping: expires_at is perf_counter-absolute (from the
    # request's deadline_ms); served_by tags the ladder rung this pending was
    # routed to; stale_embedding carries the PRE-mutation cached state so an
    # overload/breaker reroute can still serve cache_only after the window
    # already advanced (advance_user drops the embedding it certifies)
    expires_at: Optional[float] = None
    served_by: str = "primary"
    stale_embedding: Optional[np.ndarray] = None
    stale_length: int = 0
    # why this pending was degraded (breaker_open/overload); the on_degrade
    # event is emitted only AFTER its enqueue succeeds — a rerouted request
    # must produce one degrade event, for the rung that actually took it
    degrade_reason: Optional[str] = None
    # hot-swap bookkeeping (serve.promote): the traffic-slice role this
    # request routed to, and — for hit-lane pendings — the param generation
    # that encoded the cached embedding (the dispatch-time staleness guard
    # re-encodes on mismatch rather than score old states with new weights).
    # canary_epoch stamps WHICH begin_canary window admitted a candidate
    # request: a previous candidate's late-landing outcome must not count in
    # the current canary's evaluation window
    role: str = "stable"
    embedding_generation: int = 0
    canary_epoch: int = 0
    # distributed-trace context forwarded by the fleet router (the pure-JSON
    # ``TraceContext.to_json()`` payload: at least ``{"trace_id": ...}``).
    # Dispatch-side spans (queue_wait, the batch's build/score window) carry
    # its trace_id in their args so the request's replica-side time lands on
    # its timeline. None when the request arrived untraced — the default path
    # allocates nothing
    trace: Optional[dict] = None


def top_k_cut(response: "ScoreResponse", k: int) -> Tuple[np.ndarray, np.ndarray]:
    """The ranked top-k ``(item_ids, scores)`` of a response, score-descending.

    Works on BOTH response shapes: full-catalog (``item_ids is None`` —
    ``argpartition`` picks the k best of ``scores`` without sorting the whole
    catalog) and ranked/candidate responses (``item_ids`` present — re-ranked,
    because the candidate-gather path returns scores in REQUEST order). Ties
    break by original position (stable), and ``k`` is clamped to the available
    items. This is the one shared cut used by the quality monitor and the
    bench clients instead of private argsort copies.
    """
    scores = np.asarray(response.scores).reshape(-1)
    if response.item_ids is None:
        k = min(int(k), scores.shape[0])
        if k <= 0:
            return np.empty(0, np.int64), np.empty(0, scores.dtype)
        part = np.argpartition(scores, scores.shape[0] - k)[scores.shape[0] - k :]
        order = part[np.argsort(-scores[part], kind="stable")]
        return order.astype(np.int64), scores[order]
    item_ids = np.asarray(response.item_ids).reshape(-1)
    k = min(int(k), item_ids.shape[0])
    if k <= 0:
        return np.empty(0, item_ids.dtype), np.empty(0, scores.dtype)
    order = np.argsort(-scores[: item_ids.shape[0]], kind="stable")[:k]
    return item_ids[order], scores[order]


def make_window(
    items: Sequence[int], max_sequence_length: int, pad_id: int = 0
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Right-align ``items`` into a ``[L]`` window (the canonical serving
    layout, matching ``SequenceBatcher``'s left padding): returns
    ``(window, mask, length)`` keeping only the most recent ``L`` events."""
    length = min(len(items), max_sequence_length)
    window = np.full(max_sequence_length, pad_id, np.int32)
    mask = np.zeros(max_sequence_length, bool)
    if length:
        window[max_sequence_length - length :] = np.asarray(items, np.int32)[-length:]
        mask[max_sequence_length - length :] = True
    return window, mask, length
