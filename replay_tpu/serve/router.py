"""Fleet routing primitives: the hash ring, replica health, retry backoff.

The host-side half of scaling :class:`~replay_tpu.serve.ScoringService` out
to N replicas (``serve/fleet.py``). Everything here is stdlib-only and
jax-free, so the routing logic is testable (and schedulable) without a device
in sight — the same split as ``batcher``/``breaker`` vs ``engine``.

* :class:`HashRing` — consistent hashing with virtual nodes. Users map to a
  point on a 64-bit ring; the owning replica is the first vnode clockwise.
  Adding or removing ONE replica remaps only the keys whose arcs it
  gains/loses — ~1/N of the population — so the per-user state caches on the
  other replicas stay hot through membership changes (bounded movement is
  measured, not assumed: ``tests/serve/test_router.py``). The hash is
  deterministic across processes (blake2b, no PYTHONHASHSEED dependence),
  like :func:`~replay_tpu.serve.promote.in_canary_slice`.
* :class:`ReplicaHealth` — the per-replica health state machine
  ``healthy → degraded → draining → dead`` the fleet's monitor drives from
  heartbeats plus each replica's own exporter gauges (lane depth, breaker
  state, error rate). ``healthy``/``degraded`` replicas take traffic
  (degraded ones only as a home replica, never as a hedge/failover target);
  ``draining`` replicas finish their in-flight work but accept nothing new
  (the weight-swap window); ``dead`` replicas are skipped entirely and their
  users fail over to the next replica on the ring.
* :class:`BackoffPolicy` — capped exponential backoff for router-level
  retries that HONORS the service's own ``retry_after_s`` hint: a
  :class:`~replay_tpu.serve.errors.RequestShed` carries the shedding lane's
  backlog-drain estimate, and retrying earlier than that is just load the
  lane already refused once.
"""

from __future__ import annotations

import hashlib
import threading
import time
from bisect import bisect_right
from typing import Any, Callable, Dict, Hashable, List, Optional, Tuple

__all__ = ["REPLICA_HEALTH", "BackoffPolicy", "HashRing", "ReplicaHealth"]

# health states in degradation order; the first two accept traffic
REPLICA_HEALTH = ("healthy", "degraded", "draining", "dead")

# which transitions the state machine accepts (anything else raises: a fleet
# that silently "revives" a draining replica mid-swap is exactly the bug this
# table exists to refuse)
_TRANSITIONS = {
    "healthy": ("degraded", "draining", "dead"),
    "degraded": ("healthy", "draining", "dead"),
    "draining": ("healthy", "dead"),
    "dead": ("healthy",),
}


def _hash64(key: Hashable) -> int:
    """Deterministic 64-bit ring position (process-independent: every router
    in the fleet — and every process of a multi-host driver — must agree on
    where a user lives)."""
    digest = hashlib.blake2b(repr(key).encode(), digest_size=8).digest()
    return int.from_bytes(digest, "big")


class HashRing:
    """Consistent-hash ring mapping users to replica ids, with vnodes.

    :param replicas: initial replica ids (any hashable, typically strings).
    :param vnodes: virtual nodes per replica — more vnodes = smoother load
        split and smaller movement variance on membership changes, at O(R x V)
        ring size. 64 keeps the max/mean load imbalance within ~20% for small
        fleets.

    Thread-safe: routing reads and membership writes share one lock (routing
    is a bisect over a sorted list — the lock is nanoseconds, not a choke
    point at serving rates).
    """

    def __init__(self, replicas: Tuple[Hashable, ...] = (), vnodes: int = 64) -> None:
        if vnodes < 1:
            msg = f"vnodes must be >= 1, got {vnodes}"
            raise ValueError(msg)
        self.vnodes = int(vnodes)
        self._lock = threading.Lock()
        self._points: List[Tuple[int, Hashable]] = []  # sorted by hash
        self._replicas: Dict[Hashable, List[int]] = {}
        for replica in replicas:
            self.add(replica)

    def __len__(self) -> int:
        with self._lock:
            return len(self._replicas)

    @property
    def replicas(self) -> List[Hashable]:
        with self._lock:
            return list(self._replicas)

    def add(self, replica_id: Hashable) -> None:
        with self._lock:
            if replica_id in self._replicas:
                return
            hashes = [
                _hash64((replica_id, vnode)) for vnode in range(self.vnodes)
            ]
            self._replicas[replica_id] = hashes
            self._points.extend((h, replica_id) for h in hashes)
            self._points.sort()

    def remove(self, replica_id: Hashable) -> None:
        with self._lock:
            if replica_id not in self._replicas:
                return
            del self._replicas[replica_id]
            self._points = [p for p in self._points if p[1] != replica_id]

    def route(self, user_id: Hashable) -> Hashable:
        """The user's HOME replica (first vnode clockwise of the user's hash).

        Membership-only function of the ring: health is the fleet's concern —
        a dead home replica means :meth:`preference`'s NEXT entry serves, and
        the user comes back home on revival (their cache is still there).
        """
        preference = self.preference(user_id, limit=1)
        if not preference:
            msg = "hash ring is empty (no replicas registered)"
            raise LookupError(msg)
        return preference[0]

    def preference(self, user_id: Hashable, limit: Optional[int] = None) -> List[Hashable]:
        """Distinct replicas in ring order starting at the user's hash point —
        the failover/hedge order: entry 0 is home, entry 1 is where the user
        fails over (and is therefore the hedge target), and so on."""
        with self._lock:
            if not self._points:
                return []
            if limit is None:
                limit = len(self._replicas)
            start = bisect_right(self._points, (_hash64(user_id), chr(0x10FFFF)))
            seen: List[Hashable] = []
            for offset in range(len(self._points)):
                replica = self._points[(start + offset) % len(self._points)][1]
                if replica not in seen:
                    seen.append(replica)
                    if len(seen) >= limit:
                        break
            return seen

    def spread(self, sample: int = 10_000) -> Dict[Hashable, float]:
        """Fraction of ``sample`` synthetic keys landing on each replica —
        the load-balance introspection number (and the test's material)."""
        counts: Dict[Hashable, int] = {}
        for key in range(sample):
            home = self.route(("spread", key))
            counts[home] = counts.get(home, 0) + 1
        return {replica: count / sample for replica, count in counts.items()}


class ReplicaHealth:
    """One replica's health state + transition log.

    The fleet's monitor owns the SIGNALS (heartbeat liveness, lane-depth /
    breaker / error-rate gauges); this class owns the legal transitions and
    the audit trail. ``transition()`` returns whether the state actually
    changed, so callers emit exactly one event per real change.
    """

    def __init__(self, replica_id: Hashable, clock: Callable[[], float] = time.monotonic) -> None:
        self.replica_id = replica_id
        self._clock = clock
        self.state = "healthy"
        self.reason = "start"
        self.since = clock()
        self.consecutive_heartbeat_misses = 0
        # recent transitions only — the durable audit trail is the
        # on_replica_health event stream; a flapping replica must not grow
        # process memory without bound
        self.transitions: List[Dict[str, Any]] = []
        self.transition_count = 0

    @property
    def takes_traffic(self) -> bool:
        """Whether the router may send NEW requests here (home traffic)."""
        return self.state in ("healthy", "degraded")

    @property
    def takes_failover(self) -> bool:
        """Whether rerouted/hedged traffic may land here. Stricter than
        :attr:`takes_traffic`: piling another replica's users onto an
        already-degraded one is how one failure becomes two."""
        return self.state == "healthy"

    def transition(self, to: str, reason: str = "") -> bool:
        """Move to ``to`` (returns False when already there); raises on a
        transition the state machine does not allow."""
        if to not in REPLICA_HEALTH:
            msg = f"unknown health state {to!r} (expected one of {REPLICA_HEALTH})"
            raise ValueError(msg)
        if to == self.state:
            return False
        if to not in _TRANSITIONS[self.state]:
            msg = (
                f"replica {self.replica_id!r}: illegal health transition "
                f"{self.state} -> {to} ({reason or 'no reason'})"
            )
            raise ValueError(msg)
        record = {
            "replica": self.replica_id,
            "from": self.state,
            "to": to,
            "reason": reason,
            "at": self._clock(),
        }
        self.state = to
        self.reason = reason
        self.since = record["at"]
        self.transitions.append(record)
        self.transition_count += 1
        if len(self.transitions) > 512:
            del self.transitions[:256]
        return True

    def snapshot(self) -> Dict[str, Any]:
        return {
            "replica": self.replica_id,
            "state": self.state,
            "reason": self.reason,
            "since": self.since,
            "heartbeat_misses": self.consecutive_heartbeat_misses,
            "transitions": self.transition_count,
        }


class BackoffPolicy:
    """Capped exponential backoff honoring the service's retry-after hint.

    ``delay(attempt)`` grows ``base * multiplier**attempt`` up to ``cap``;
    when the refusal carried a ``retry_after_s`` (the shed lane's own
    backlog-drain estimate), the delay is never SHORTER than that hint —
    retrying into a lane that told you when it will have room is the one
    retry pattern that cannot help.
    """

    def __init__(
        self,
        base_s: float = 0.01,
        multiplier: float = 2.0,
        cap_s: float = 1.0,
        max_retries: int = 2,
    ) -> None:
        if base_s < 0 or cap_s < 0 or multiplier < 1.0:
            msg = (
                f"backoff needs base_s>=0, cap_s>=0, multiplier>=1 "
                f"(got {base_s}, {cap_s}, {multiplier})"
            )
            raise ValueError(msg)
        self.base_s = float(base_s)
        self.multiplier = float(multiplier)
        self.cap_s = float(cap_s)
        self.max_retries = int(max_retries)

    def delay(self, attempt: int, retry_after_s: Optional[float] = None) -> float:
        """Seconds to wait before retry number ``attempt`` (0-based)."""
        try:
            grown = self.base_s * self.multiplier ** max(int(attempt), 0)
        except OverflowError:
            # multiplier**attempt past float range (~2.0**1024): the growth is
            # monotonic, so the cap is the exact answer — never an exception
            # out of a retry scheduler
            grown = float("inf")
        backoff = min(grown, self.cap_s)
        if retry_after_s is not None:
            # the hint wins when it is LONGER; the cap still bounds the total
            backoff = min(max(backoff, float(retry_after_s)), max(self.cap_s, float(retry_after_s)))
        return backoff

    def exhausted(self, attempt: int) -> bool:
        return int(attempt) >= self.max_retries
