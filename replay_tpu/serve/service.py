"""The on-device scoring service: micro-batched, state-cached, rank-fused.

Orchestrates the serve subsystem end-to-end::

    client threads ──submit()──▶ MicroBatcher lanes ──▶ serve worker
                                                        ├─ encode lane: window
                                                        │  batch → CompiledInference
                                                        │  bucket executable
                                                        ├─ hit lane: cached [E]
                                                        │  states → hidden scorer
                                                        └─ retrieval: MIPS top-C
                                                           → re-rank → top-k

Three serving modes, fixed at construction (one compiled program family each):

* **full** (default): responses carry full-catalog scores, an exact host
  top-k cut, or exact gathers for per-request candidate lists.
* **slate** (``candidates=...``): every response scores one fixed candidate
  slate compiled into the executables (the reference's ``candidates_to_score``
  serving shape).
* **retrieval** (``retrieval=CandidatePipeline(...)``): the fused
  candidate→rank path — full-catalog logits never materialize.

Parity contract (tested in ``tests/serve/``): response scores are BITWISE
identical to a direct AOT ``forward_inference`` call on the same right-aligned
window at the routed (length, batch) bucket — and within a bucket program they
are bitwise independent of the fill level, the co-riding requests' content,
and the row order, so micro-batching and caching never change a score. (The
bucket qualifier is XLA reality: the same math compiled at two different batch
shapes may differ in the last float ulp; every response carries its
``batch_bucket`` so the exact program is always reconstructible.)

Observability: requests record ``queue_wait`` spans (cross-thread, via
``obs.trace.lifecycle_span``), batches record ``batch_build``/``score`` and
the pipeline's ``retrieve``/``rerank`` spans; ``on_serve_start`` /
``on_serve_batch`` / ``on_serve_end`` events flow through any
:class:`~replay_tpu.obs.RunLogger`, and ``on_serve_end`` carries the serve
goodput breakdown (``SERVE_GOODPUT_SPANS`` fractions, summing to 1.0).
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Any, Dict, Hashable, List, Optional, Sequence, Tuple

import numpy as np

from replay_tpu.obs import TrainerEvent, Tracer
from replay_tpu.obs.trace import SERVE_GOODPUT_SPANS, goodput_breakdown, lifecycle_span

from .batcher import MicroBatcher
from .cache import UserState, UserStateCache
from .engine import ScoringEngine
from .pipeline import CandidatePipeline
from .request import PendingRequest, ScoreRequest, ScoreResponse, make_window


class ScoringService:
    """Thread-safe online scoring over a trained sequential model."""

    def __init__(
        self,
        model,
        params,
        length_buckets: Optional[Sequence[int]] = None,
        batch_buckets: Sequence[int] = (1, 8, 64),
        max_wait_ms: float = 2.0,
        cache_capacity: int = 10_000,
        candidates: Optional[Sequence[int]] = None,
        retrieval: Optional[CandidatePipeline] = None,
        feature_name: str = "item_id",
        pad_id: int = 0,
        tracer: Optional[Tracer] = None,
        logger=None,
        trace_path: Optional[str] = None,
    ) -> None:
        if retrieval is not None and candidates is not None:
            msg = "retrieval mode and a fixed candidate slate are mutually exclusive"
            raise ValueError(msg)
        self.mode = (
            "retrieval" if retrieval is not None
            else "slate" if candidates is not None
            else "full"
        )
        self.retrieval = retrieval
        self.pad_id = int(pad_id)
        self.tracer = tracer if tracer is not None else Tracer(enabled=False)
        self.logger = logger
        self.trace_path = trace_path
        self.engine = ScoringEngine(
            model,
            params,
            length_buckets=length_buckets,
            batch_buckets=batch_buckets,
            candidates=np.asarray(candidates, np.int32) if candidates is not None else None,
            feature_name=feature_name,
            outputs="hidden" if retrieval is not None else "both",
        )
        self.cache = UserStateCache(cache_capacity)
        self.batcher = MicroBatcher(
            dispatch=self._dispatch,
            capacity=max(self.engine.batch_buckets),
            max_wait=max_wait_ms / 1000.0,
            on_error=self._on_dispatch_error,
        )
        self._count_lock = threading.Lock()
        self._requests = 0
        self._errors = 0
        self._served_from: Dict[str, int] = {"hit": 0, "advance": 0, "cold": 0}
        self._queue_wait_sum = 0.0
        self._queue_wait_max = 0.0
        self._goodput_t0: Dict[str, float] = {}
        self._wall_t0 = 0.0
        self._started = False

    # -- lifecycle ---------------------------------------------------------- #
    def start(self) -> "ScoringService":
        if self._started:
            return self
        self._started = True
        self._goodput_t0 = self.tracer.snapshot()
        self._wall_t0 = self.tracer.wall_seconds()
        self.batcher.start()
        self._emit(
            "on_serve_start",
            {
                "mode": self.mode,
                "length_buckets": list(self.engine.length_buckets),
                "batch_buckets": list(self.engine.batch_buckets),
                "max_wait_ms": self.batcher.max_wait * 1000.0,
                "cache_capacity": self.cache.capacity,
            },
        )
        return self

    def close(self) -> None:
        if not self._started:
            return
        self.batcher.stop()
        self._started = False
        payload = dict(self.stats())
        snapshot = self.tracer.snapshot()
        diff = {
            name: snapshot.get(name, 0.0) - self._goodput_t0.get(name, 0.0)
            for name in set(snapshot) | set(self._goodput_t0)
        }
        payload["goodput"] = goodput_breakdown(
            diff,
            self.tracer.wall_seconds() - self._wall_t0,
            spans=SERVE_GOODPUT_SPANS,
        )
        self._emit("on_serve_end", payload)
        if self.trace_path and self.tracer.enabled:
            self.tracer.save(self.trace_path)

    def __enter__(self) -> "ScoringService":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.close()

    # -- client API --------------------------------------------------------- #
    def submit(
        self,
        user_id: Hashable,
        history: Optional[Sequence[int]] = None,
        new_items: Sequence[int] = (),
        k: Optional[int] = None,
        candidates: Optional[Sequence[int]] = None,
    ) -> "Future[ScoreResponse]":
        """Enqueue one scoring request; resolves to a :class:`ScoreResponse`."""
        future: "Future[ScoreResponse]" = Future()
        request = ScoreRequest(
            user_id=user_id,
            history=history,
            new_items=tuple(new_items),
            k=k,
            candidates=candidates,
        )
        with self._count_lock:
            self._requests += 1
        try:
            lane, pending = self._resolve(request, future)
            self.batcher.submit(lane, pending)
        except Exception as exc:  # noqa: BLE001 — surface through the future
            with self._count_lock:
                self._errors += 1
            future.set_exception(exc)
        return future

    def score(self, user_id, timeout: Optional[float] = 60.0, **kwargs) -> ScoreResponse:
        """Synchronous :meth:`submit`."""
        return self.submit(user_id, **kwargs).result(timeout=timeout)

    # -- request resolution (client thread) --------------------------------- #
    def _resolve(
        self, request: ScoreRequest, future: "Future[ScoreResponse]"
    ) -> Tuple[Hashable, PendingRequest]:
        if request.candidates is not None and self.mode != "full":
            msg = (
                f"per-request candidates need the full-scoring service "
                f"(this one runs in {self.mode!r} mode)"
            )
            raise ValueError(msg)
        if request.k is not None and self.retrieval is not None:
            if request.k > self.retrieval.top_k:
                msg = (
                    f"k={request.k} exceeds the pipeline's compiled "
                    f"top_k={self.retrieval.top_k}"
                )
                raise ValueError(msg)
        max_len = self.engine.max_sequence_length

        if request.history is not None:
            # the exact-parity fallback: an explicit history always wins and
            # re-anchors the cached state
            items = list(request.history) + list(request.new_items)
            if not items:
                msg = "empty history"
                raise ValueError(msg)
            window, mask, length = make_window(items, max_len, self.pad_id)
            previous = self.cache.peek(request.user_id)
            state = UserState(
                window=window,
                mask=mask,
                length=length,
                embedding=None,
                generation=previous.generation + 1 if previous else 0,
            )
            self.cache.store(request.user_id, state)
            return self._encode_pending(request, future, state, "cold")

        if request.new_items:
            # atomic lookup+advance+store: concurrent appends for one user
            # must both land (an unlocked read-modify-write would let the
            # last store erase the other's interaction)
            advanced = self.cache.advance_user(
                request.user_id, request.new_items, self.pad_id
            )
            if advanced is None:
                msg = (
                    f"user {request.user_id!r} has no cached state; "
                    "provide history= for the cold path"
                )
                raise KeyError(msg)
            return self._encode_pending(request, future, advanced, "advance")
        state = self.cache.lookup(request.user_id)
        if state is None:
            msg = (
                f"user {request.user_id!r} has no cached state; "
                "provide history= for the cold path"
            )
            raise KeyError(msg)
        if state.embedding is not None:
            pending = PendingRequest(
                request=request,
                future=future,
                served_from="hit",
                embedding=state.embedding,
                length=state.length,
                enqueued_at=self.tracer.now(),
            )
            return "hit", pending
        # cached window whose embedding is still in flight (or was raced
        # away): re-encode the cached window — still no history re-send
        return self._encode_pending(request, future, state, "advance")

    def _encode_pending(
        self,
        request: ScoreRequest,
        future: "Future[ScoreResponse]",
        state: UserState,
        served_from: str,
    ) -> Tuple[Hashable, PendingRequest]:
        length_bucket = self.engine.route_length(state.length)
        pending = PendingRequest(
            request=request,
            future=future,
            served_from=served_from,
            window=state.window,
            mask=state.mask,
            length=state.length,
            enqueued_at=self.tracer.now(),
            extra=(state,),
        )
        return ("encode", length_bucket), pending

    # -- dispatch (serve-worker thread) ------------------------------------- #
    def _on_dispatch_error(self, lane, items: List[PendingRequest], exc: BaseException) -> None:
        with self._count_lock:
            self._errors += len(items)
        for item in items:
            if not item.future.done():
                item.future.set_exception(exc)

    def _lane_name(self, lane) -> str:
        return "hit" if lane == "hit" else f"encode:L={lane[1]}"

    def _dispatch(self, lane, items: List[PendingRequest]) -> None:
        waits = [
            lifecycle_span(self.tracer, "queue_wait", item.enqueued_at, lane=self._lane_name(lane))
            for item in items
        ]
        rows = len(items)
        bucket = self.engine.batch_bucket(rows)
        if lane == "hit":
            with self.tracer.span("batch_build", rows=rows):
                hidden = np.stack([item.embedding for item in items]).astype(np.float32)
            if self.retrieval is not None:
                self.engine.record_ranked_batch(rows, bucket)
                scores, ids = self._rank(hidden, rows, bucket)
                logits = None
            else:
                with self.tracer.span("score", rows=rows, lane="hit"):
                    logits = np.asarray(self.engine.score_hidden(hidden))
                scores = ids = None
        else:
            _, length_bucket = lane
            with self.tracer.span("batch_build", rows=rows):
                ids_batch = np.stack([item.window[-length_bucket:] for item in items])
                mask_batch = np.stack([item.mask[-length_bucket:] for item in items])
            with self.tracer.span("score", rows=rows, lane=self._lane_name(lane)):
                logits_dev, hidden_dev = self.engine.encode(length_bucket, ids_batch, mask_batch)
                hidden_np = np.asarray(hidden_dev)
                logits = np.asarray(logits_dev) if logits_dev is not None else None
            for item, embedding in zip(items, hidden_np):
                state = item.extra[0]
                self.cache.refresh_embedding(item.request.user_id, state, embedding)
            if self.retrieval is not None:
                scores, ids = self._rank(hidden_np, rows, bucket)
            else:
                scores = ids = None

        for row, (item, wait) in enumerate(zip(items, waits)):
            try:
                response = self._build_response(
                    item,
                    lane_name=self._lane_name(lane),
                    batch_bucket=bucket,
                    queue_wait=wait,
                    logits_row=logits[row] if logits is not None else None,
                    ranked_scores=scores[row] if scores is not None else None,
                    ranked_ids=ids[row] if ids is not None else None,
                )
            except Exception as exc:  # noqa: BLE001
                with self._count_lock:
                    self._errors += 1
                item.future.set_exception(exc)
                continue
            with self._count_lock:
                self._served_from[item.served_from] += 1
                self._queue_wait_sum += wait
                self._queue_wait_max = max(self._queue_wait_max, wait)
            item.future.set_result(response)

        self._emit(
            "on_serve_batch",
            {
                "lane": self._lane_name(lane),
                "rows": rows,
                "bucket": bucket,
                "fill": rows / bucket if bucket else 0.0,
                "queue_wait_ms_max": max(waits) * 1000.0 if waits else 0.0,
            },
        )

    def _rank(self, hidden: np.ndarray, rows: int, bucket: int):
        """Run the fused retrieve→rerank path at the padded batch bucket —
        the pipeline's jitted programs then only ever see the bucket ladder's
        shapes (no per-fill retrace)."""
        if rows < bucket:
            hidden = np.concatenate([hidden, np.repeat(hidden[:1], bucket - rows, 0)])
        scores, ids = self.retrieval.rank(hidden, tracer=self.tracer)
        return scores[:rows], ids[:rows]

    def _build_response(
        self,
        item: PendingRequest,
        lane_name: str,
        batch_bucket: int,
        queue_wait: float,
        logits_row: Optional[np.ndarray],
        ranked_scores: Optional[np.ndarray],
        ranked_ids: Optional[np.ndarray],
    ) -> ScoreResponse:
        request = item.request
        if self.retrieval is not None:
            k = request.k if request.k is not None else self.retrieval.top_k
            scores, item_ids = ranked_scores[:k], ranked_ids[:k]
        elif self.mode == "slate":
            scores, item_ids = logits_row, np.asarray(self.engine.candidates)
            if request.k is not None:
                order = np.argsort(-scores, kind="stable")[: request.k]
                scores, item_ids = scores[order], item_ids[order]
        else:
            if request.candidates is not None:
                gathered = np.asarray(request.candidates, np.int64)
                scores, item_ids = logits_row[gathered], gathered
            elif request.k is not None:
                order = np.argsort(-logits_row, kind="stable")[: request.k]
                scores, item_ids = logits_row[order], order
            else:
                scores, item_ids = logits_row, None
        return ScoreResponse(
            user_id=request.user_id,
            scores=np.asarray(scores),
            item_ids=np.asarray(item_ids) if item_ids is not None else None,
            served_from=item.served_from,
            lane=lane_name,
            queue_wait_s=queue_wait,
            batch_bucket=batch_bucket,
        )

    # -- accounting --------------------------------------------------------- #
    def _emit(self, event: str, payload: Dict[str, Any]) -> None:
        if self.logger is not None:
            self.logger.log_event(TrainerEvent(event=event, payload=payload))

    def stats(self) -> Dict[str, Any]:
        engine = self.engine.stats()
        cache = self.cache.stats()
        batcher = self.batcher.stats()
        with self._count_lock:
            served = dict(self._served_from)
            requests = self._requests
            errors = self._errors
            wait_sum = self._queue_wait_sum
            wait_max = self._queue_wait_max
        answered = sum(served.values())
        reused = served["hit"] + served["advance"]
        return {
            "mode": self.mode,
            "requests": requests,
            "answered": answered,
            "errors": errors,
            "served_from": served,
            # state reuse: requests served from cached state (pure hits +
            # one-step advances) over answered requests
            "cache_hit_rate": reused / answered if answered else 0.0,
            "pure_hit_rate": served["hit"] / answered if answered else 0.0,
            "batch_fill_ratio": engine["batch_fill_ratio"],
            "queue_wait_ms_mean": wait_sum / answered * 1000.0 if answered else 0.0,
            "queue_wait_ms_max": wait_max * 1000.0,
            "engine": engine,
            "cache": cache,
            "batcher": batcher,
        }
